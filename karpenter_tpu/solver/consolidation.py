"""Batched consolidation what-ifs on the device.

The Go reference evaluates consolidation candidates one simulated scheduling
pass at a time (SURVEY.md §3.3); this module vectorizes the dominant
questions — "which single nodes could be deleted, with their pods absorbed by
the rest of the cluster?" and "which node *subsets* could be deleted
together?" — over EVERY candidate at once (SURVEY §7.6: "multi-node candidate
subsets on-TPU ... the big win vs the Go heuristic").

Formulation: for candidate subset S, greedily pack the union of S's pods
(largest first, same FFD key as the solvers) into the non-members' residual
capacity, honoring per-(source, target) label/taint compatibility.  One
``vmap`` over subsets of one ``lax.scan`` over padded pod slots; state is the
[N, R] residual matrix per subset.  Dense, regular, MXU/VPU-friendly — and
one device call for the whole screen.

The kernel is a single module-level jit over shape-bucketed arrays, so
steady-state controller reconciles hit the persistent jit cache instead of
recompiling (same pattern as solver/tpu.py's _run_scan).  The screen is
resource+compat only: topology constraints are NOT evaluated here, so the
deprovisioning controller exact-confirms every hit with the sequential
what-if before acting.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics import (
    CONSOLIDATION_SWEEP_DURATION,
    CONSOLIDATION_SWEEP_SLOTS,
    CONSOLIDATION_SWEEPS,
    Registry,
)
from ..gang import nodes_carry_gangs
from ..models import labels as L
from ..obs.trace import NULL_TRACE
from .types import SimNode, SolveResult, node_classes

logger = logging.getLogger(__name__)

_RESOURCES = (L.RESOURCE_CPU, L.RESOURCE_MEMORY, L.RESOURCE_PODS)


@dataclass
class DeleteScreenResult:
    deletable: np.ndarray        # [N] bool — pods fit on other nodes
    n_candidates: int
    eval_ms: float
    compile_ms: float


@dataclass
class SubsetScreenResult:
    deletable: np.ndarray        # [K] bool — subset's pods fit on non-members
    n_subsets: int
    eval_ms: float
    compile_ms: float


def _ffd_key(p) -> float:
    return -(p.requests.get(L.RESOURCE_CPU, 0.0)
             + p.requests.get(L.RESOURCE_MEMORY, 0.0) / (4 * 1024.0**3))


def _bucket(n: int, q: int) -> int:
    return max(q, ((n + q - 1) // q) * q)


@jax.jit
def _screen_kernel(residual, member, pods, src, compat):
    """[K] bool: per subset, does a greedy first-fit place every pod of the
    member nodes onto compatible non-member residuals?"""

    def one_subset(member_k, pods_k, src_k):
        res0 = jnp.where(member_k[:, None], 0.0, residual)

        def place(res, args):
            pod, s = args
            ok_t = compat[s] & ~member_k
            fits = jnp.all(res + 1e-6 >= pod[None, :], axis=1) & ok_t
            any_fit = jnp.any(fits)
            idx = jnp.argmax(fits)
            is_real = jnp.any(pod > 0)
            deduct = jnp.where(is_real & any_fit, pod, 0.0)
            res = res.at[idx].add(-deduct)
            return res, jnp.where(is_real, any_fit, True)

        _, oks = jax.lax.scan(place, res0, (pods_k, src_k))
        return jnp.all(oks)

    return jax.vmap(one_subset)(member, pods, src)


# ktlint: fence the screen IS the sync point — one dispatch + one D2H read
# whose result gates which candidates enter the sweep; the deprovisioning
# tick blocks on it by design (KT013: the fence bounds the whole screen)
def screen_subset_deletes(
    nodes: Sequence[SimNode],
    subsets: Sequence[Sequence[int]],   # K subsets of node indices
    compat: Optional[np.ndarray] = None,
    pmax_total: int = 128,
    measure: bool = False,
) -> SubsetScreenResult:
    """One device call: for every candidate subset, can the union of its
    members' pods fit on the non-members' residual capacity?

    Pods carry their source-node index so ``compat`` stays per-(source,
    target).  Subsets whose pod union exceeds ``pmax_total`` are
    conservatively marked undeletable.  With ``measure=True`` the kernel runs
    twice to split compile_ms from steady-state eval_ms (benchmarks); the
    default single run is what control loops want.
    """
    t0 = time.perf_counter()
    N = len(nodes)
    K = len(subsets)
    R = len(_RESOURCES)
    # shape bucketing -> persistent jit-cache hits across reconciles
    NP_ = _bucket(N, 256)
    KP = _bucket(K, 8)

    residual = np.zeros((NP_, R), dtype=np.float32)
    for i, n in enumerate(nodes):
        rem = n.remaining()
        residual[i] = [max(0.0, rem.get(r, 0.0)) for r in _RESOURCES]

    member = np.zeros((KP, NP_), dtype=bool)
    pods_mat = np.zeros((KP, pmax_total, R), dtype=np.float32)
    pods_src = np.zeros((KP, pmax_total), dtype=np.int32)
    overflow = np.zeros(KP, dtype=bool)
    pods_ridx = _RESOURCES.index(L.RESOURCE_PODS)
    for k, subset in enumerate(subsets):
        member[k, list(subset)] = True
        entries = [(_ffd_key(p), i, p) for i in subset for p in nodes[i].pods]
        if len(entries) > pmax_total:
            overflow[k] = True
            continue
        entries.sort(key=lambda e: e[0])
        for j, (_, i, p) in enumerate(entries):
            for r, name in enumerate(_RESOURCES):
                pods_mat[k, j, r] = p.requests.get(name, 0.0)
            pods_mat[k, j, pods_ridx] = 1.0
            pods_src[k, j] = i

    cm = np.zeros((NP_, NP_), dtype=bool)
    if compat is None:
        cm[:N, :N] = True
    else:
        cm[:N, :N] = compat

    args = (jnp.asarray(residual), jnp.asarray(member), jnp.asarray(pods_mat),
            jnp.asarray(pods_src), jnp.asarray(cm))
    # NOTE: timings include the (tiny) result readback — block_until_ready
    # can report completion early through the device tunnel, faking ~0ms
    # evals; a D2H read of the result is the only reliable fence observed
    out_host = np.asarray(_screen_kernel(*args))
    first_ms = (time.perf_counter() - t0) * 1000.0
    if measure:
        # median of 3 timed runs on per-run perturbed residuals (outputs
        # discarded): the device runtime also memoizes executions of
        # identical (executable, inputs)
        rng = np.random.default_rng(0)
        times = []
        for _ in range(3):
            res_i = residual + rng.uniform(0.0, 1e-5, residual.shape).astype(np.float32)
            # ktlint: allow[KT011] measure=True benchmark branch only: the
            # perturbed re-placement defeats the runtime's execution memo;
            # the serving path (measure=False) never reaches this
            args_i = (jax.device_put(res_i),) + args[1:]
            jax.block_until_ready(args_i[0])
            t1 = time.perf_counter()
            np.asarray(_screen_kernel(*args_i))
            times.append((time.perf_counter() - t1) * 1000.0)
        eval_ms = sorted(times)[1]
        compile_ms = first_ms
    else:
        eval_ms, compile_ms = first_ms, 0.0

    return SubsetScreenResult(
        deletable=out_host[:K] & ~overflow[:K],
        n_subsets=K, eval_ms=eval_ms, compile_ms=compile_ms,
    )


def screen_delete_candidates(
    nodes: Sequence[SimNode],
    compat: Optional[np.ndarray] = None,
    pmax: int = 64,
    measure: bool = False,
) -> DeleteScreenResult:
    """Single-node screen = the subset screen over all singletons.  A
    candidate's own capacity never counts (it is the deleted node)."""
    if compat is not None:
        compat = compat.copy()
        np.fill_diagonal(compat, False)
    else:
        compat = ~np.eye(len(nodes), dtype=bool)
    res = screen_subset_deletes(
        nodes, [[i] for i in range(len(nodes))], compat,
        pmax_total=pmax, measure=measure,
    )
    return DeleteScreenResult(
        deletable=res.deletable, n_candidates=len(nodes),
        eval_ms=res.eval_ms, compile_ms=res.compile_ms,
    )


def compat_matrix(
    nodes: Sequence[SimNode],
    sources: Optional[Sequence[int]] = None,
) -> np.ndarray:
    """Host-side label/taint compatibility: pods of node i can run on node j.

    ``sources`` limits the computed rows to those node indices (the screen
    only reads rows for member/candidate nodes) — O(|sources| * N) string
    work instead of O(N^2); uncomputed rows stay False.  Conservative: every
    pod of i must tolerate j's taints and have its node-selector satisfied by
    j's labels (full requirement algebra — the exact sequential what-if
    re-verifies anything the screen admits).
    """
    N = len(nodes)
    src = range(N) if sources is None else sources
    out = np.zeros((N, N), dtype=bool)

    # The naive O(|sources| x N x pods) requirement-algebra walk repeats the
    # same few questions millions of times at 5k nodes (~100 s of the 5k
    # consolidation reconcile).  Two-level memo instead:
    #  - a POD SIGNATURE is exactly what node-compat depends on — the pod's
    #    effective requirement set (node_selector + required affinity term
    #    0) plus its tolerations.  Requests/labels/owner do NOT widen it
    #    (group_key would: unique requests -> unique keys -> no dedup).
    #  - a DESTINATION CLASS is the node's taints plus only the label keys
    #    any source pod's requirements actually reference — a unique
    #    per-node hostname label must not split an otherwise uniform fleet
    #    into N classes when nothing selects on hostname.
    # The 5k bench fleet asks 1 question instead of 87M.
    pod_sig: Dict[int, tuple] = {}        # id(pod) -> signature
    sig_reqs: Dict[tuple, object] = {}    # signature -> Requirements
    relevant_keys: set = set()
    for i in src:
        for p in nodes[i].pods:
            reqs = p.scheduling_requirements()[0]
            # Requirements.signature() is the lossless structural key
            # (to_list()'s canonical operator form would collide
            # [Exists(k), NotIn(k,{x})] with [NotIn(k,{x})])
            key = (reqs.signature(), tuple(p.tolerations))
            pod_sig[id(p)] = key
            if key not in sig_reqs:
                sig_reqs[key] = reqs
                relevant_keys.update(reqs)

    cls_idx, class_rep = node_classes(nodes, relevant_keys)
    dst_class = np.asarray(cls_idx, dtype=np.int64)
    n_cls = len(class_rep)

    sig_cls_ok: Dict[tuple, np.ndarray] = {}  # signature -> [n_cls] bool

    def sig_ok_row(key: tuple) -> np.ndarray:
        row = sig_cls_ok.get(key)
        if row is None:
            reqs = sig_reqs[key]
            tols = key[1]  # the signature's second element IS the tolerations
            row = np.zeros(n_cls, dtype=bool)
            for c, dst in enumerate(class_rep):
                row[c] = (
                    not any(t.blocks(tols) for t in dst.taints)
                    and reqs.compatible(dst.labels) is None
                )
            sig_cls_ok[key] = row
        return row

    for i in src:
        node_i = nodes[i]
        if not node_i.pods:
            out[i, :] = True
            out[i, i] = False
            continue
        ok_cls = np.ones(n_cls, dtype=bool)
        for p in node_i.pods:
            ok_cls &= sig_ok_row(pod_sig[id(p)])
            if not ok_cls.any():
                break
        out[i] = ok_cls[dst_class]
        out[i, i] = False
    return out


# ---------------------------------------------------------------------------
# one-dispatch consolidation what-if sweeps
# ---------------------------------------------------------------------------
#
# The deprovisioning controller used to pay one full scheduler round trip per
# candidate what-if ("can this node's pods fit on the rest of the cluster
# plus at most one new node?") — N candidates, N dispatches, N fences.  Every
# candidate's what-if is a PERTURBATION of one base solution (the cluster
# with all nodes active): same catalog tensors, same existing-node state,
# only the member rows and the displaced pods differ.  The sweep exploits
# that: ONE shared host-array build of the base cluster, per-candidate
# derivations (deactivate the member rows, subtract their selector/limit
# contributions, swap in the candidate's counts), and ONE vmapped device
# dispatch + ONE fence for the whole sweep via TpuSolver.solve_many_prepared
# (the megabatch path of solver/tpu.py).
#
# Exactness contract: a slot whose device answer is anything but a clean
# "all pods fit on the survivors, no new node" is re-solved through the
# serial scheduler path (full relaxation/residue/reseat ladder), so sweep
# decisions are identical to the sequential what-if loop; per-slot boxed
# exceptions keep one poisoned candidate from failing its batchmates.  The
# sweep's vmapped program compiles behind (TpuSolver.warm_custom) — the
# first sweeps of a shape serve serially, never stalling a reconcile on XLA.

#: sweep candidates per vmapped dispatch (chunked above this)
SWEEP_MAX_SLOTS = 16


@dataclass
class SweepOutcome:
    """One consolidation what-if sweep: per-candidate results IN ORDER —
    a SolveResult, the Exception that candidate alone raised, or None for
    slots past a ``stop_on`` early exit (never evaluated)."""

    results: List[object]
    path: str                # "batched" | "serial" | "mixed"
    wall_ms: float
    n_batched: int = 0
    n_serial: int = 0
    dispatches: int = 0      # vmapped device dispatches (fences) paid


#: sweep execution paths — the zero-inited label population of
#: karpenter_solver_consolidation_sweeps_total (KT003)
SWEEP_PATHS = ("batched", "mixed", "serial")


def zero_init_sweep_metrics(registry: Registry) -> None:
    """Register the sweep series at 0 (KT003)."""
    for path in SWEEP_PATHS:
        if not registry.counter(CONSOLIDATION_SWEEPS).has({"path": path}):
            registry.counter(CONSOLIDATION_SWEEPS).inc(
                {"path": path}, value=0.0)
    registry.histogram(CONSOLIDATION_SWEEP_SLOTS)
    registry.histogram(CONSOLIDATION_SWEEP_DURATION)


def sweep_dims(st, NE: int, node_budget: int, track: bool = False) -> dict:
    """What-if-sized padded dims: the standard :func:`tpu.solve_dims`
    bucketing with FINE small-solve rungs on the G and NR axes.  A what-if
    places a handful of groups against a known node count; the serving-path
    rungs (G quantum 16, NR floor 512) would run the scan at 4-8x the
    state the sweep needs.  Confined to the sweep's own compile ladder —
    serving-path signatures are untouched."""
    from .tpu import _rung, solve_dims

    dims = solve_dims(st, NE=NE, node_budget=node_budget, track=track,
                      full_nr=True)
    if st.G <= 16:
        dims["G"] = _rung(st.G, 4, 16)
    if node_budget <= 512:
        dims["NR"] = _rung(max(1, node_budget), 64, 512)
    return dims


def sweep_signature(st, dims: dict, slots: int, mesh=None) -> tuple:
    """Compile signature of the sweep's vmapped program at a slot rung —
    the key TpuSolver readiness/warm bookkeeping tracks for it.  With a
    ``mesh``, the SHARDED sweep program: slot rung floored at the device
    count, mesh fingerprint in the key (the shared ``_mega_key_tail``
    format ``_dispatch_prepared`` keys dispatches with)."""
    from .tpu import _dims_key, _mega_key_tail

    return _dims_key(dims) + _mega_key_tail(
        slots, st.vocab.key_id[L.ZONE], st.vocab.key_id[L.CAPACITY_TYPE],
        mesh,
    )


def build_sweep_entries(
    solver,
    sts: Sequence[object],
    all_nodes: Sequence[SimNode],
    members: Sequence[Sequence[int]],
    dims: dict,
    node_budget: int,
    trace=None,
) -> List[dict]:
    """Derive one megabatch entry per candidate from ONE shared base build.

    Every candidate's what-if shares the base cluster's host arrays
    (residuals, compat, selector counts, provisioner usage over ALL nodes);
    a candidate differs only by (a) its member node rows being deactivated
    — an inactive row can never receive pods, which is exactly "this node
    is deleted" — (b) its members' selector/zone/provisioner contributions
    subtracted from the seeded counters, and (c) its own pods' counts
    tensors.  All ``sts`` must share one group structure (the shape-tier
    tensorize guarantee the caller groups by) and one ``dims`` bucket.
    """
    from .tpu import host_count_arrays

    st0 = sts[0]
    N = len(all_nodes)
    track = bool(dims["track"])
    np_consts0, feas0, np_init0, _ = solver._host_arrays(
        st0, all_nodes, node_budget=node_budget,
        track_assignments=track, full_nr=True, dims=dims,
    )
    (ex_res, ex_zone, row_dom, row_cand, ex_price, ex_sel, active0,
     n_used0, zc0, tot0, prov_used0, infeas0) = np_init0
    pad_g = dims["G"] - st0.G
    Z = dims["Z"]
    prov_index = {n: i for i, n in enumerate(st0.prov_names)}

    entries: List[dict] = []
    for st_k, member in zip(sts, members):
        counts, _req, suffix_res, suffix_cnt = host_count_arrays(
            st_k, pad_g, Z)
        consts_k = dict(np_consts0, counts=counts, suffix_res=suffix_res,
                        suffix_cnt=suffix_cnt)
        active = active0.copy()
        zc = zc0.copy()
        tot = tot0.copy()
        prov_used = prov_used0.copy()
        for idx in member:
            active[idx] = False
            sel_row = ex_sel[idx]
            if sel_row.size:
                zc[:, ex_zone[idx]] -= sel_row
                tot -= sel_row
            node = all_nodes[idx]
            pi = prov_index.get(node.provisioner)
            if pi is not None:
                prov_used[pi] = prov_used[pi] - st0.capacity_row(
                    node.instance_type, node.allocatable)
        init_k = (ex_res, ex_zone, row_dom, row_cand, ex_price, ex_sel,
                  active, n_used0, zc, tot, prov_used, infeas0)
        entries.append(dict(
            r=dict(st=st_k, existing_nodes=(), max_nodes=node_budget,
                   track_assignments=track, raise_on_exhaust=False,
                   trace=trace or NULL_TRACE),
            np_consts=consts_k, feas=feas0, np_init=init_k, dims=dims,
            est_dims=dims, full_dims=dims, full_nr=True, NE=N,
        ))
    return entries


# ktlint: fence the warm thunk's D2H read is the deliberate compile+fence of
# the background sweep-program warm (discarded results, warm thread only)
def _warm_sweep(solver, entries: List[dict], slots: int, sig: tuple,
                mesh=None) -> None:
    """Background-compile the sweep's vmapped program — the SHARDED one for
    a meshed scheduler (compile-behind: the serving sweep never stalls on
    XLA)."""

    def thunk():
        from .tpu import read_slot_rows

        pending = solver.solve_many_prepared(entries, min_slots=slots,
                                             mesh=mesh)
        # fence: the compile has landed.  Through the addressable-shard
        # accessor (KT018): on a multi-process mesh the warm thread owns
        # only its local shards — a whole-batch read would crash (and
        # pay DCN) for a result it discards anyway
        read_slot_rows([pending.carry_b[7]], local_only=mesh is not None)
        solver._mark_ready(sig)

    solver.warm_custom(sig, thunk)


def sweep_what_ifs(
    scheduler,
    all_nodes: Sequence[SimNode],
    candidates: Sequence[Sequence[int]],
    *,
    provisioners,
    instance_types,
    daemonsets: Sequence = (),
    unavailable=None,
    max_new: int = 1,
    registry: Optional[Registry] = None,
    trace=None,
    stop_on=None,
) -> SweepOutcome:
    """Evaluate every candidate's what-if ("delete these nodes; do their
    pods fit on the rest plus at most ``max_new`` new nodes?") — batched as
    slots of one vmapped device dispatch when the device path is warm,
    serially through ``scheduler.solve`` otherwise.  ``candidates`` are
    node-index subsets of ``all_nodes``.  Results are in candidate order;
    decisions are identical to the sequential what-if loop by construction
    (non-clean slots re-solve serially).

    ``stop_on(k, result)`` — optional early exit for the SERIAL fill, for
    callers that take the first confirming candidate in order (the loop
    this sweep replaced stopped there too): evaluated on every slot in
    candidate order — batched and serial alike — and once it returns True
    the remaining unresolved slots stay ``None`` instead of paying a full
    what-if solve each for answers the caller will never read.  Batched
    slots themselves always resolve (they arrive together in the one
    dispatch, already paid for)."""
    t0 = time.perf_counter()
    registry = registry or scheduler.registry
    zero_init_sweep_metrics(registry)
    trace = trace or NULL_TRACE
    from ..models.tensorize import batch_needs_oracle, device_inexpressible
    from .scheduler import _harden_preferences
    from .tpu import _dims_key

    K = len(candidates)
    results: List[object] = [None] * K
    n_batched = n_serial = dispatches = 0

    def serial_one(k: int) -> object:
        member = set(candidates[k])
        others = [n for j, n in enumerate(all_nodes) if j not in member]
        pods = [p for idx in candidates[k]
                for p in all_nodes[idx].pods if not p.is_daemon]
        try:
            return scheduler.solve(
                pods, provisioners, instance_types, existing_nodes=others,
                daemonsets=daemonsets, unavailable=unavailable,
                allow_new_nodes=True, max_new_nodes=max_new,
                trace=trace,
            )
        # ktlint: allow[KT005] per-candidate boxed outcome: one poisoned
        # what-if must not fail the sweep's batchmates; the controller
        # re-raises or skips per candidate
        except Exception as err:  # noqa: BLE001
            return err

    # whole-sweep device eligibility; per-candidate carve-outs below.
    # Meshed schedulers sweep SHARDED (slot axis over the mesh's chips,
    # one dispatch + one fence, same as single-device); only a mesh whose
    # device count exceeds the slot-rung ladder keeps the serial path —
    # explicitly metriced via the existing path="serial" label.
    from .tpu import mesh_shardable

    mesh = scheduler.mesh
    device_ok = (
        scheduler.backend in ("auto", "tpu")
        and mesh_shardable(mesh)
        and scheduler._tensorize_cache is not None
        and (scheduler.backend == "tpu" or not scheduler._guard.enabled
             or scheduler._guard.healthy)
    )

    N = len(all_nodes)
    node_budget = N + (max_new if max_new is not None else 0)
    buckets: Dict[tuple, List[int]] = {}
    prepared: Dict[int, tuple] = {}   # k -> (st, dims, skey)
    if device_ok:
        for k in range(K):
            pods = [p for idx in candidates[k]
                    for p in all_nodes[idx].pods if not p.is_daemon]
            if not pods:
                # empty candidate: trivially deletable, same as the serial
                # scheduler.solve([]) answer
                results[k] = SolveResult(nodes=[], assignments={},
                                         infeasible={})
                continue
            if nodes_carry_gangs([all_nodes[i] for i in candidates[k]]):
                # gang what-ifs re-seat the ENTIRE gang or the candidate
                # fails (ISSUE 20): only the serial path's gang epilogue
                # audits that (preseated-comember counting, typed
                # retraction) — the vmapped slot answer has no epilogue
                continue
            try:
                hardened = [_harden_preferences(p) for p in pods]
                if (batch_needs_oracle(hardened)
                        or any(device_inexpressible(p) for p in hardened)):
                    continue  # oracle-coupled shapes: serial path
                st, _tier = scheduler._tensorize_cache.tensorize(
                    hardened, provisioners, instance_types,
                    daemonsets=daemonsets, unavailable=unavailable,
                )
                dims = sweep_dims(st, N, node_budget)
                skey = tuple(g.key for g in st.groups)
                bkey = (_dims_key(dims), st.vocab.key_id[L.ZONE],
                        st.vocab.key_id[L.CAPACITY_TYPE])
                prepared[k] = (st, dims, skey)
                buckets.setdefault(bkey, []).append(k)
            # ktlint: allow[KT005] an unbatchable candidate just solves on
            # the serial path, where a real error surfaces with context
            except Exception:  # noqa: BLE001
                logger.debug("sweep candidate %d not batchable; serial",
                             k, exc_info=True)

    solver = scheduler._tpu if device_ok else None
    for bkey, idxs in buckets.items():
        for lo in range(0, len(idxs), SWEEP_MAX_SLOTS):
            chunk = idxs[lo:lo + SWEEP_MAX_SLOTS]
            st0, dims, _ = prepared[chunk[0]]
            sig = sweep_signature(st0, dims, len(chunk), mesh=mesh)
            if not solver.ready(sig) and solver.warm_pending(sig):
                # compile-behind already in flight: this sweep serves
                # serially anyway, so skip the shared-base host build
                # (entries are only needed to dispatch or to SEED a warm)
                continue
            # one base build per group structure within the chunk
            by_skey: Dict[tuple, List[int]] = {}
            for k in chunk:
                by_skey.setdefault(prepared[k][2], []).append(k)
            entry_of: Dict[int, dict] = {}
            for ks in by_skey.values():
                entries = build_sweep_entries(
                    solver, [prepared[k][0] for k in ks], all_nodes,
                    [candidates[k] for k in ks], prepared[ks[0]][1],
                    node_budget, trace=trace,
                )
                for k, e in zip(ks, entries):
                    entry_of[k] = e
            chunk_entries = [entry_of[k] for k in chunk]
            if not solver.ready(sig):
                # compile-behind: serve this sweep serially, warm the
                # vmapped program in the background
                _warm_sweep(solver, chunk_entries, len(chunk), sig,
                            mesh=mesh)
                continue
            try:
                with trace.span("sweep_dispatch", slots=len(chunk)):
                    outs = solver.solve_many_prepared(
                        chunk_entries, min_slots=len(chunk),
                        mesh=mesh).results()
            # ktlint: allow[KT005] a failed sweep dispatch degrades the
            # whole chunk to the proven serial path (decisions unchanged)
            except Exception:  # noqa: BLE001
                logger.warning("sweep dispatch failed; chunk served "
                               "serially", exc_info=True)
                continue
            dispatches += 1
            registry.histogram(CONSOLIDATION_SWEEP_SLOTS).observe(len(chunk))
            for k, out in zip(chunk, outs):
                if isinstance(out, BaseException):
                    continue  # serial below (boxed per-slot degrade)
                res = out.result
                if res.infeasible or res.nodes:
                    # not a clean "fits on the survivors" answer: the
                    # serial path's repair ladder (residue waves, reseat,
                    # replacement sizing) must judge it — exact parity
                    continue
                results[k] = res
                n_batched += 1

    for k in range(K):
        if results[k] is None:
            results[k] = serial_one(k)
            n_serial += 1
        # evaluated on EVERY slot in candidate order — batched slots too,
        # so a dispatch-confirmed early candidate stops the serial fill
        # before it pays for later unbatchable ones the caller won't read
        if stop_on is not None and stop_on(k, results[k]):
            break

    wall_ms = (time.perf_counter() - t0) * 1000.0
    # "serial" means serial FALLBACKS ran — a sweep resolved entirely by
    # pre-dispatch shortcuts (no solve on either path) stays "batched" so
    # the serial-fallback rate only counts real degradation
    path = ("serial" if n_serial and not n_batched
            else "mixed" if n_serial else "batched")
    registry.counter(CONSOLIDATION_SWEEPS).inc({"path": path})
    registry.histogram(CONSOLIDATION_SWEEP_DURATION).observe(wall_ms / 1000.0)
    return SweepOutcome(results=results, path=path, wall_ms=wall_ms,
                        n_batched=n_batched, n_serial=n_serial,
                        dispatches=dispatches)
