"""Batched consolidation what-ifs on the device.

The Go reference evaluates consolidation candidates one simulated scheduling
pass at a time (SURVEY.md §3.3); this module vectorizes the dominant
questions — "which single nodes could be deleted, with their pods absorbed by
the rest of the cluster?" and "which node *subsets* could be deleted
together?" — over EVERY candidate at once (SURVEY §7.6: "multi-node candidate
subsets on-TPU ... the big win vs the Go heuristic").

Formulation: for candidate subset S, greedily pack the union of S's pods
(largest first, same FFD key as the solvers) into the non-members' residual
capacity, honoring per-(source, target) label/taint compatibility.  One
``vmap`` over subsets of one ``lax.scan`` over padded pod slots; state is the
[N, R] residual matrix per subset.  Dense, regular, MXU/VPU-friendly — and
one device call for the whole screen.

The kernel is a single module-level jit over shape-bucketed arrays, so
steady-state controller reconciles hit the persistent jit cache instead of
recompiling (same pattern as solver/tpu.py's _run_scan).  The screen is
resource+compat only: topology constraints are NOT evaluated here, so the
deprovisioning controller exact-confirms every hit with the sequential
what-if before acting.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import labels as L
from .types import SimNode, node_classes

_RESOURCES = (L.RESOURCE_CPU, L.RESOURCE_MEMORY, L.RESOURCE_PODS)


@dataclass
class DeleteScreenResult:
    deletable: np.ndarray        # [N] bool — pods fit on other nodes
    n_candidates: int
    eval_ms: float
    compile_ms: float


@dataclass
class SubsetScreenResult:
    deletable: np.ndarray        # [K] bool — subset's pods fit on non-members
    n_subsets: int
    eval_ms: float
    compile_ms: float


def _ffd_key(p) -> float:
    return -(p.requests.get(L.RESOURCE_CPU, 0.0)
             + p.requests.get(L.RESOURCE_MEMORY, 0.0) / (4 * 1024.0**3))


def _bucket(n: int, q: int) -> int:
    return max(q, ((n + q - 1) // q) * q)


@jax.jit
def _screen_kernel(residual, member, pods, src, compat):
    """[K] bool: per subset, does a greedy first-fit place every pod of the
    member nodes onto compatible non-member residuals?"""

    def one_subset(member_k, pods_k, src_k):
        res0 = jnp.where(member_k[:, None], 0.0, residual)

        def place(res, args):
            pod, s = args
            ok_t = compat[s] & ~member_k
            fits = jnp.all(res + 1e-6 >= pod[None, :], axis=1) & ok_t
            any_fit = jnp.any(fits)
            idx = jnp.argmax(fits)
            is_real = jnp.any(pod > 0)
            deduct = jnp.where(is_real & any_fit, pod, 0.0)
            res = res.at[idx].add(-deduct)
            return res, jnp.where(is_real, any_fit, True)

        _, oks = jax.lax.scan(place, res0, (pods_k, src_k))
        return jnp.all(oks)

    return jax.vmap(one_subset)(member, pods, src)


def screen_subset_deletes(
    nodes: Sequence[SimNode],
    subsets: Sequence[Sequence[int]],   # K subsets of node indices
    compat: Optional[np.ndarray] = None,
    pmax_total: int = 128,
    measure: bool = False,
) -> SubsetScreenResult:
    """One device call: for every candidate subset, can the union of its
    members' pods fit on the non-members' residual capacity?

    Pods carry their source-node index so ``compat`` stays per-(source,
    target).  Subsets whose pod union exceeds ``pmax_total`` are
    conservatively marked undeletable.  With ``measure=True`` the kernel runs
    twice to split compile_ms from steady-state eval_ms (benchmarks); the
    default single run is what control loops want.
    """
    t0 = time.perf_counter()
    N = len(nodes)
    K = len(subsets)
    R = len(_RESOURCES)
    # shape bucketing -> persistent jit-cache hits across reconciles
    NP_ = _bucket(N, 256)
    KP = _bucket(K, 8)

    residual = np.zeros((NP_, R), dtype=np.float32)
    for i, n in enumerate(nodes):
        rem = n.remaining()
        residual[i] = [max(0.0, rem.get(r, 0.0)) for r in _RESOURCES]

    member = np.zeros((KP, NP_), dtype=bool)
    pods_mat = np.zeros((KP, pmax_total, R), dtype=np.float32)
    pods_src = np.zeros((KP, pmax_total), dtype=np.int32)
    overflow = np.zeros(KP, dtype=bool)
    pods_ridx = _RESOURCES.index(L.RESOURCE_PODS)
    for k, subset in enumerate(subsets):
        member[k, list(subset)] = True
        entries = [(_ffd_key(p), i, p) for i in subset for p in nodes[i].pods]
        if len(entries) > pmax_total:
            overflow[k] = True
            continue
        entries.sort(key=lambda e: e[0])
        for j, (_, i, p) in enumerate(entries):
            for r, name in enumerate(_RESOURCES):
                pods_mat[k, j, r] = p.requests.get(name, 0.0)
            pods_mat[k, j, pods_ridx] = 1.0
            pods_src[k, j] = i

    cm = np.zeros((NP_, NP_), dtype=bool)
    if compat is None:
        cm[:N, :N] = True
    else:
        cm[:N, :N] = compat

    args = (jnp.asarray(residual), jnp.asarray(member), jnp.asarray(pods_mat),
            jnp.asarray(pods_src), jnp.asarray(cm))
    # NOTE: timings include the (tiny) result readback — block_until_ready
    # can report completion early through the device tunnel, faking ~0ms
    # evals; a D2H read of the result is the only reliable fence observed
    out_host = np.asarray(_screen_kernel(*args))
    first_ms = (time.perf_counter() - t0) * 1000.0
    if measure:
        # median of 3 timed runs on per-run perturbed residuals (outputs
        # discarded): the device runtime also memoizes executions of
        # identical (executable, inputs)
        rng = np.random.default_rng(0)
        times = []
        for _ in range(3):
            res_i = residual + rng.uniform(0.0, 1e-5, residual.shape).astype(np.float32)
            args_i = (jax.device_put(res_i),) + args[1:]
            jax.block_until_ready(args_i[0])
            t1 = time.perf_counter()
            np.asarray(_screen_kernel(*args_i))
            times.append((time.perf_counter() - t1) * 1000.0)
        eval_ms = sorted(times)[1]
        compile_ms = first_ms
    else:
        eval_ms, compile_ms = first_ms, 0.0

    return SubsetScreenResult(
        deletable=out_host[:K] & ~overflow[:K],
        n_subsets=K, eval_ms=eval_ms, compile_ms=compile_ms,
    )


def screen_delete_candidates(
    nodes: Sequence[SimNode],
    compat: Optional[np.ndarray] = None,
    pmax: int = 64,
    measure: bool = False,
) -> DeleteScreenResult:
    """Single-node screen = the subset screen over all singletons.  A
    candidate's own capacity never counts (it is the deleted node)."""
    if compat is not None:
        compat = compat.copy()
        np.fill_diagonal(compat, False)
    else:
        compat = ~np.eye(len(nodes), dtype=bool)
    res = screen_subset_deletes(
        nodes, [[i] for i in range(len(nodes))], compat,
        pmax_total=pmax, measure=measure,
    )
    return DeleteScreenResult(
        deletable=res.deletable, n_candidates=len(nodes),
        eval_ms=res.eval_ms, compile_ms=res.compile_ms,
    )


def compat_matrix(
    nodes: Sequence[SimNode],
    sources: Optional[Sequence[int]] = None,
) -> np.ndarray:
    """Host-side label/taint compatibility: pods of node i can run on node j.

    ``sources`` limits the computed rows to those node indices (the screen
    only reads rows for member/candidate nodes) — O(|sources| * N) string
    work instead of O(N^2); uncomputed rows stay False.  Conservative: every
    pod of i must tolerate j's taints and have its node-selector satisfied by
    j's labels (full requirement algebra — the exact sequential what-if
    re-verifies anything the screen admits).
    """
    N = len(nodes)
    src = range(N) if sources is None else sources
    out = np.zeros((N, N), dtype=bool)

    # The naive O(|sources| x N x pods) requirement-algebra walk repeats the
    # same few questions millions of times at 5k nodes (~100 s of the 5k
    # consolidation reconcile).  Two-level memo instead:
    #  - a POD SIGNATURE is exactly what node-compat depends on — the pod's
    #    effective requirement set (node_selector + required affinity term
    #    0) plus its tolerations.  Requests/labels/owner do NOT widen it
    #    (group_key would: unique requests -> unique keys -> no dedup).
    #  - a DESTINATION CLASS is the node's taints plus only the label keys
    #    any source pod's requirements actually reference — a unique
    #    per-node hostname label must not split an otherwise uniform fleet
    #    into N classes when nothing selects on hostname.
    # The 5k bench fleet asks 1 question instead of 87M.
    pod_sig: Dict[int, tuple] = {}        # id(pod) -> signature
    sig_reqs: Dict[tuple, object] = {}    # signature -> Requirements
    relevant_keys: set = set()
    for i in src:
        for p in nodes[i].pods:
            reqs = p.scheduling_requirements()[0]
            # Requirements.signature() is the lossless structural key
            # (to_list()'s canonical operator form would collide
            # [Exists(k), NotIn(k,{x})] with [NotIn(k,{x})])
            key = (reqs.signature(), tuple(p.tolerations))
            pod_sig[id(p)] = key
            if key not in sig_reqs:
                sig_reqs[key] = reqs
                relevant_keys.update(reqs)

    cls_idx, class_rep = node_classes(nodes, relevant_keys)
    dst_class = np.asarray(cls_idx, dtype=np.int64)
    n_cls = len(class_rep)

    sig_cls_ok: Dict[tuple, np.ndarray] = {}  # signature -> [n_cls] bool

    def sig_ok_row(key: tuple) -> np.ndarray:
        row = sig_cls_ok.get(key)
        if row is None:
            reqs = sig_reqs[key]
            tols = key[1]  # the signature's second element IS the tolerations
            row = np.zeros(n_cls, dtype=bool)
            for c, dst in enumerate(class_rep):
                row[c] = (
                    not any(t.blocks(tols) for t in dst.taints)
                    and reqs.compatible(dst.labels) is None
                )
            sig_cls_ok[key] = row
        return row

    for i in src:
        node_i = nodes[i]
        if not node_i.pods:
            out[i, :] = True
            out[i, i] = False
            continue
        ok_cls = np.ones(n_cls, dtype=bool)
        for p in node_i.pods:
            ok_cls &= sig_ok_row(pod_sig[id(p)])
            if not ok_cls.any():
                break
        out[i] = ok_cls[dst_class]
        out[i, i] = False
    return out
