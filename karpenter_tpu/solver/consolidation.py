"""Batched consolidation what-ifs on the device.

The Go reference evaluates consolidation candidates one simulated scheduling
pass at a time (SURVEY.md §3.3); this module vectorizes the dominant question
— "which single nodes could be deleted, with their pods absorbed by the rest
of the cluster?" — over EVERY candidate at once (SURVEY §7.6: "multi-node
candidate subsets on-TPU ... the big win vs the Go heuristic").

Formulation: for candidate node i, greedily pack node i's pods (largest
first, same FFD key as the solvers) into the other nodes' residual capacity,
honoring label/taint compatibility.  One ``vmap`` over candidates of one
``lax.scan`` over padded pod slots; state is the [N, R] residual matrix per
candidate.  A cluster of N nodes with <= Pmax pods per candidate costs
O(N^2 * Pmax * R) flops — dense, regular, MXU/VPU-friendly — and returns a
boolean per node in a single device call.

The deprovisioning controller uses this as a *screen*: provably-deletable
candidates are then confirmed by the exact sequential what-if (cheap, since
the screen already filtered), preserving decision parity while cutting the
evaluation count by orders of magnitude on big clusters (BASELINE config #4).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import labels as L
from .types import SimNode


@dataclass
class DeleteScreenResult:
    deletable: np.ndarray        # [N] bool — pods fit on other nodes
    n_candidates: int
    eval_ms: float
    compile_ms: float


def _pod_rows(node: SimNode, resources: List[str], pmax: int) -> np.ndarray:
    rows = np.zeros((pmax, len(resources)), dtype=np.float32)
    pods = sorted(
        node.pods,
        key=lambda p: -(p.requests.get(L.RESOURCE_CPU, 0.0)
                        + p.requests.get(L.RESOURCE_MEMORY, 0.0) / (4 * 1024.0**3)),
    )[:pmax]
    for i, p in enumerate(pods):
        for r, name in enumerate(resources):
            rows[i, r] = p.requests.get(name, 0.0)
        # the pods resource
        if L.RESOURCE_PODS in resources:
            rows[i, resources.index(L.RESOURCE_PODS)] = 1.0
    return rows


def screen_delete_candidates(
    nodes: Sequence[SimNode],
    compat: Optional[np.ndarray] = None,   # [N, N] pod-source x target compat
    pmax: int = 64,
) -> DeleteScreenResult:
    """One device call: for every node i, can its pods (up to ``pmax``) fit on
    the other nodes' residual capacity?

    ``compat[i, j]``: pods of node i may run on node j (labels/taints checked
    host-side once — O(N^2) string work, amortized by the vectorized pack).
    Nodes with more than ``pmax`` pods are conservatively marked undeletable.
    """
    t0 = time.perf_counter()
    N = len(nodes)
    resources = [L.RESOURCE_CPU, L.RESOURCE_MEMORY, L.RESOURCE_PODS]
    R = len(resources)

    residual = np.zeros((N, R), dtype=np.float32)
    pods_mat = np.zeros((N, pmax, R), dtype=np.float32)
    overflow = np.zeros(N, dtype=bool)
    for i, node in enumerate(nodes):
        rem = node.remaining()
        for r, name in enumerate(resources):
            residual[i, r] = max(0.0, rem.get(name, 0.0))
        pods_mat[i] = _pod_rows(node, resources, pmax)
        overflow[i] = len(node.pods) > pmax

    if compat is None:
        compat = np.ones((N, N), dtype=bool)
    np.fill_diagonal(compat, False)  # a candidate's own capacity doesn't count

    residual_j = jnp.asarray(residual)
    pods_j = jnp.asarray(pods_mat)
    compat_j = jnp.asarray(compat)

    @jax.jit
    def run():
        def one_candidate(pods_i, compat_i):
            # residuals of the *other* nodes (candidate's own rows masked out)
            res0 = jnp.where(compat_i[:, None], residual_j, 0.0)

            def place(res, pod):
                # first-fit: lowest-index node where every resource fits
                fits = jnp.all(res + 1e-6 >= pod[None, :], axis=1)
                # a zero pod (padding) fits anywhere; mark index 0, deduct 0
                any_fit = jnp.any(fits)
                idx = jnp.argmax(fits)
                is_real = jnp.any(pod > 0)
                deduct = jnp.where(is_real & any_fit, pod, 0.0)
                res = res.at[idx].add(-deduct)
                ok = jnp.where(is_real, any_fit, True)
                return res, ok

            _, oks = jax.lax.scan(place, res0, pods_i)
            return jnp.all(oks)

        return jax.vmap(one_candidate)(pods_j, compat_j)

    out = run()
    jax.block_until_ready(out)
    compile_ms = (time.perf_counter() - t0) * 1000.0
    t1 = time.perf_counter()
    out = run()
    jax.block_until_ready(out)
    eval_ms = (time.perf_counter() - t1) * 1000.0

    deletable = np.asarray(out) & ~overflow
    return DeleteScreenResult(
        deletable=deletable, n_candidates=N, eval_ms=eval_ms, compile_ms=compile_ms
    )


def compat_matrix(nodes: Sequence[SimNode]) -> np.ndarray:
    """Host-side label/taint compatibility: pods of node i can run on node j.

    Conservative: every pod of i must tolerate j's taints and have its
    node-selector satisfied by j's labels (full requirement algebra — the
    exact sequential what-if re-verifies anything the screen admits).
    """
    N = len(nodes)
    out = np.ones((N, N), dtype=bool)
    for i, src in enumerate(nodes):
        if not src.pods:
            continue
        for j, dst in enumerate(nodes):
            if i == j:
                continue
            ok = True
            for p in src.pods:
                if any(t.blocks(p.tolerations) for t in dst.taints):
                    ok = False
                    break
                reqs = p.scheduling_requirements()[0]
                if reqs.compatible(dst.labels) is not None:
                    ok = False
                    break
            out[i, j] = ok
    return out
