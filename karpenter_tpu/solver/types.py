"""Solver input/output types shared by the CPU oracle and the TPU solver."""

from __future__ import annotations

import dataclasses
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from ..models import labels as L
from ..models.instancetype import InstanceType
from ..models.pod import PodSpec, Taint
from ..models.resources import ResourceList, add, fits, subtract

_node_lock = threading.Lock()
_node_next = 0


def _next_node_idx() -> int:
    """The process-global auto-name index, lock-atomic: naming and
    :func:`advance_node_counter` must not race — a thread minting an
    index below a just-raised floor would hand out a colliding name."""
    global _node_next
    with _node_lock:
        idx = _node_next
        _node_next += 1
        return idx


def advance_node_counter(floor: int) -> None:
    """Ensure future auto-named SimNodes get indices STRICTLY ABOVE
    ``floor``.  Session restore (service/delta.py) needs this: a restarted
    process's counter starts back at 0, and a fresh proposal named
    ``node-5`` colliding with a restored chain's ``node-5`` would silently
    cross-wire assignments — the exact diverged-chain class the snapshot
    envelope exists to prevent."""
    global _node_next
    with _node_lock:
        _node_next = max(_node_next, floor + 1)


@dataclass
class SimNode:
    """A (possibly hypothetical) node the solver packs onto.

    Existing cluster nodes and solver-proposed nodes share this shape; the
    reference's equivalent is core's in-flight machine + state.Cluster node
    (SURVEY.md §2.2 state.Cluster).
    """

    instance_type: str
    provisioner: str
    zone: str
    capacity_type: str
    price: float  # $/hr
    allocatable: ResourceList
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    pods: List[PodSpec] = field(default_factory=list)
    existing: bool = False  # True for nodes already in the cluster
    name: str = ""
    created_at: float = 0.0
    expires_at: Optional[float] = None

    def __post_init__(self) -> None:
        if not self.name:
            self.name = f"node-{_next_node_idx()}"

    def used(self) -> ResourceList:
        out: ResourceList = {L.RESOURCE_PODS: float(len(self.pods))}
        for p in self.pods:
            for k, v in p.requests.items():
                out[k] = out.get(k, 0.0) + v
        return out

    def remaining(self) -> ResourceList:
        return subtract(self.allocatable, self.used())

    def fits(self, requests: ResourceList) -> bool:
        req = dict(requests)
        req.setdefault(L.RESOURCE_PODS, 1.0)
        return fits(req, self.remaining())

    def stamp_labels(self) -> "SimNode":
        """Stamp the node's own fields as labels (zone/capacity-type/type/
        provisioner/hostname), mirroring what the oracle's _create_node and
        real node objects carry — solver-built nodes must be judged by later
        waves' label-compat checks the same way labeled cluster nodes are
        (a label-less node reads as 'absent' for every selector)."""
        for k, v in (
            (L.ZONE, self.zone),
            (L.CAPACITY_TYPE, self.capacity_type),
            (L.INSTANCE_TYPE, self.instance_type),
            (L.PROVISIONER_NAME, self.provisioner),
            (L.HOSTNAME, self.name),
        ):
            if v:
                self.labels.setdefault(k, v)
        return self

    def snapshot(self) -> "SimNode":
        """Simulation copy: solvers place pods by mutating ``pods``, and a
        what-if solve (consolidation) must never leak placements into the
        caller's live node objects."""
        return dataclasses.replace(
            self,
            pods=list(self.pods),
            labels=dict(self.labels),
            taints=list(self.taints),
            allocatable=dict(self.allocatable),
        )


@dataclass
class SolveResult:
    """Outcome of one scheduling solve."""

    nodes: List[SimNode]                    # newly proposed nodes (with pods bound)
    assignments: Dict[str, str]             # pod name -> node name (incl. existing)
    infeasible: Dict[str, str]              # pod name -> reason
    existing_nodes: List[SimNode] = field(default_factory=list)
    solve_ms: float = 0.0
    #: host tensorize time spent producing this result (all waves), ms
    tensorize_ms: float = 0.0
    #: any wave was served by a transient cold-tier fallback (compile-behind
    #: / slots-exhausted).  Carried on the result — not on the scheduler —
    #: so pipelined solves in flight together can't clobber each other's
    #: flag; the reseat epilogue skips polished cold answers (they are
    #: superseded once the device program compiles).
    served_cold: bool = False

    @property
    def new_node_cost(self) -> float:
        return sum(n.price for n in self.nodes)

    @property
    def n_scheduled(self) -> int:
        return len(self.assignments)

    def summary(self) -> str:
        per_type: Dict[str, int] = {}
        for n in self.nodes:
            per_type[n.instance_type] = per_type.get(n.instance_type, 0) + 1
        types = ", ".join(f"{k}x{v}" for k, v in sorted(per_type.items()))
        return (
            f"{self.n_scheduled} pods -> {len(self.nodes)} new nodes "
            f"(${self.new_node_cost:.3f}/hr: {types}); {len(self.infeasible)} infeasible"
        )


def node_classes(
    nodes: Sequence[SimNode], relevant_keys
) -> Tuple[List[int], List[SimNode]]:
    """Collapse ``nodes`` into label/taint equivalence classes for memoized
    requirement-algebra checks (consolidation.compat_matrix,
    native.existing_compat).  Two nodes share a class iff they agree on
    every label key in ``relevant_keys`` (the keys any pod/group requirement
    references — a per-node hostname label must not split an otherwise
    uniform fleet when nothing selects on hostname) and carry identical
    taints.  Returns (class index per node, representative node per class);
    any check that reads only requirement keys + taints is class-invariant.
    """
    cls_idx: List[int] = []
    cls_rep: List[SimNode] = []
    cls_of: Dict[tuple, int] = {}
    for node in nodes:
        ckey = (
            tuple(sorted((k, v) for k, v in node.labels.items()
                         if k in relevant_keys)),
            tuple((t.key, t.value, t.effect) for t in node.taints),
        )
        c = cls_of.get(ckey)
        if c is None:
            c = cls_of[ckey] = len(cls_rep)
            cls_rep.append(node)
        cls_idx.append(c)
    return cls_idx, cls_rep
