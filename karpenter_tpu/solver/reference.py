"""CPU reference solver — faithful sequential first-fit-decreasing bin-packer.

This is the correctness oracle and cost baseline (BASELINE.md: "implement a
faithful Go-/CPU-reference FFD ... inside our repo").  Semantics follow
/root/reference/designs/bin-packing.md:28-43 (FFD: sort decreasing, first-fit
onto open nodes, new node chosen to pack maximal pods cheaply) and
website/content/en/preview/concepts/scheduling.md (requirements layering,
taints, topology spread skew checks, pod (anti-)affinity).

The TPU solver (solver/tpu.py) must match this oracle's node cost within 1.02x
on the BASELINE.json configs; both share the FFD ordering key and the
new-node scoring policy:

    score(pod, candidate, offering) = price / min(pods_per_node, remaining_in_group)

i.e. "cheapest $/pod for the pods we still have to place", reproducing
bin-packing.md step 3's "maximal number of pods at lowest cost" selection.

Implementation note: pods are placed strictly one at a time (exact sequential
semantics — each placement updates topology-spread counts before the next),
but identical pods are processed as a contiguous *group run* with per-zone
node heaps so the whole solve is O(G*N + P*Z*log N) instead of O(P*N); at
50k pods this is the difference between milliseconds and minutes, and it is
what the Go scheduler's in-flight node list achieves with incremental state.
"""

from __future__ import annotations

import heapq
import time
from collections import defaultdict
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

from ..models import labels as L
from ..models.instancetype import InstanceType
from ..models.pod import PodSpec
from ..models.provisioner import Provisioner
from ..models.requirements import IN, Requirements
from ..models.resources import ResourceList, add, fits
from ..models.tensorize import PodGroup, build_candidates, group_pods
from .types import SimNode, SolveResult


class _TopologyState:
    """Counts of selector-matching pods per zone / node / capacity-type /
    total (the reference's three topology domains, scheduling.md:303-346)."""

    def __init__(self) -> None:
        self.zone: Dict[tuple, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
        self.node: Dict[tuple, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
        self.ct: Dict[tuple, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
        self.total: Dict[tuple, int] = defaultdict(int)

    def observe(self, pod: PodSpec, zone: str, node_name: str, selectors,
                ct: str = "") -> None:
        for key, sel in selectors.items():
            if sel.matches(pod.labels):
                self.zone[key][zone] += 1
                self.node[key][node_name] += 1
                if ct:
                    self.ct[key][ct] += 1
                self.total[key] += 1


def _selector_table(pods: Sequence[PodSpec]) -> Dict[tuple, object]:
    out: Dict[tuple, object] = {}
    for p in pods:
        for tsc in p.topology_spread:
            if tsc.hard:
                out[(tsc.label_selector, tsc.topology_key, "spread")] = tsc.label_selector
        for t in p.affinity_terms:
            kind = "anti" if t.anti else "affinity"
            out[(t.label_selector, t.topology_key, kind)] = t.label_selector
    return out


class _Solver:
    def __init__(
        self,
        pods: Sequence[PodSpec],
        provisioners: Sequence[Provisioner],
        instance_types: Sequence[InstanceType],
        existing_nodes: Sequence[SimNode],
        daemonsets: Sequence[PodSpec],
        unavailable: Set[tuple],
        allow_new_nodes: bool,
        max_new_nodes: Optional[int],
    ) -> None:
        self.groups = group_pods(pods)
        self.pairs = build_candidates(provisioners, instance_types)
        self.daemonsets = daemonsets
        self.unavailable = unavailable
        self.allow_new_nodes = allow_new_nodes
        self.max_new_nodes = max_new_nodes
        self.selectors = _selector_table(
            list(pods) + [p for n in existing_nodes for p in n.pods]
        )
        self.topo = _TopologyState()
        self.nodes: List[SimNode] = list(existing_nodes)  # creation order
        self.new_nodes: List[SimNode] = []
        self.assignments: Dict[str, str] = {}
        self.infeasible: Dict[str, str] = {}
        self.prov_usage: Dict[str, ResourceList] = defaultdict(dict)
        self._label_ok_cache: Dict[tuple, bool] = {}
        self._ds_cache: Dict[Tuple[str, str], ResourceList] = {}
        # per-node caches keyed by identity (nodes are this solve's private
        # snapshots): label signature never changes mid-solve; remaining()
        # changes only on _bind, which invalidates.  The heap build calls
        # _group_cap for every (group, node) pair — at 2k existing nodes the
        # uncached remaining()/sig-sort work dominated consolidation
        # what-ifs (O(G*N) * O(pods_per_node))
        self._sig_cache: Dict[int, tuple] = {}
        self._rem_cache: Dict[int, ResourceList] = {}
        # label keys any group's requirements reference: _node_sig keeps only
        # these, so a per-node hostname label doesn't split an otherwise
        # uniform fleet into N signatures and defeat _label_ok_cache (the
        # heap build asks _label_taint_ok for every (group, node) pair —
        # O(G*N) requirement-algebra walks at consolidation-what-if scale
        # without the collapse)
        self._relevant_keys: Set[str] = set()
        for g in self.groups:
            self._relevant_keys.update(g.requirements)

        self.all_zones: List[str] = []
        for _, _, it, _ in self.pairs:
            for o in it.offerings:
                if o.zone not in self.all_zones:
                    self.all_zones.append(o.zone)

        # limits bind on raw machine CAPACITY (the validator and creation-time
        # checks both use it.capacity); counting existing nodes at allocatable
        # under-counts their usage by the reserved overhead and lets the last
        # new node overshoot the limit (fuzz seed 23)
        raw_cap = {it.name: it.capacity for _, _, it, _ in self.pairs}
        for n in existing_nodes:
            cap = raw_cap.get(n.instance_type, n.allocatable)
            self.prov_usage[n.provisioner] = add(
                self.prov_usage[n.provisioner],
                {L.RESOURCE_CPU: cap.get(L.RESOURCE_CPU, 0.0),
                 L.RESOURCE_MEMORY: cap.get(L.RESOURCE_MEMORY, 0.0)},
            )
            for p in n.pods:
                self.topo.observe(p, n.zone, n.name, self.selectors,
                                  ct=n.capacity_type)

    # ---- per-(group,node-shape) caches --------------------------------
    def _node_sig(self, node: SimNode) -> tuple:
        sig = self._sig_cache.get(id(node))
        if sig is None:
            sig = (
                node.instance_type, node.provisioner, node.capacity_type,
                tuple(sorted((k, v) for k, v in node.labels.items()
                             if k in self._relevant_keys)),
                tuple(node.taints),
            )
            self._sig_cache[id(node)] = sig
        return sig

    def _remaining(self, node: SimNode) -> ResourceList:
        rem = self._rem_cache.get(id(node))
        if rem is None:
            rem = node.remaining()
            self._rem_cache[id(node)] = rem
        return rem

    def _label_taint_ok(self, g: PodGroup, node: SimNode) -> bool:
        key = (id(g), self._node_sig(node))
        got = self._label_ok_cache.get(key)
        if got is None:
            rep = g.pods[0]
            got = not any(t.blocks(rep.tolerations) for t in node.taints) and (
                g.requirements.compatible(node.labels) is None
            )
            self._label_ok_cache[key] = got
        return got

    def _daemon_overhead(self, prov: Provisioner, it: InstanceType) -> ResourceList:
        key = (prov.name, it.name)
        got = self._ds_cache.get(key)
        if got is None:
            total: ResourceList = {}
            labels = {**it.labels(), **prov.labels}
            for d in self.daemonsets:
                if any(t.blocks(d.tolerations) for t in prov.taints):
                    continue
                if any(r.compatible(labels) is not None for r in d.scheduling_requirements()):
                    continue
                total = add(total, d.requests)
                total[L.RESOURCE_PODS] = total.get(L.RESOURCE_PODS, 0.0) + 1.0
            self._ds_cache[key] = got = total
        return got

    # ---- topology checks -----------------------------------------------
    def _zone_allowed(self, g: PodGroup, zone: str, eligible: Sequence[str]) -> bool:
        rep = g.pods[0]
        for tsc in rep.topology_spread:
            if not tsc.hard or tsc.topology_key != L.ZONE:
                continue
            key = (tsc.label_selector, L.ZONE, "spread")
            counts = self.topo.zone[key]
            min_count = min((counts.get(z, 0) for z in eligible), default=0)
            if counts.get(zone, 0) + 1 - min_count > tsc.max_skew:
                return False
        for term in rep.affinity_terms:
            if term.topology_key != L.ZONE:
                continue
            key = (term.label_selector, L.ZONE, "anti" if term.anti else "affinity")
            if term.anti:
                if self.topo.zone[key].get(zone, 0) > 0:
                    return False
            else:
                if self.topo.total[key] > 0:
                    if self.topo.zone[key].get(zone, 0) == 0:
                        return False
                elif not term.matches_pod(rep):
                    return False
        return True

    def _ct_allowed(self, g: PodGroup, ct: str, eligible: Sequence[str]) -> bool:
        """Hard capacity-type topology spread (scheduling.md:303-346 — the
        third supported topologyKey; the canonical use is spreading replicas
        across spot/on-demand to bound the interruption blast radius)."""
        rep = g.pods[0]
        for tsc in rep.topology_spread:
            if not tsc.hard or tsc.topology_key != L.CAPACITY_TYPE:
                continue
            key = (tsc.label_selector, L.CAPACITY_TYPE, "spread")
            counts = self.topo.ct[key]
            min_count = min((counts.get(c, 0) for c in eligible), default=0)
            if counts.get(ct, 0) + 1 - min_count > tsc.max_skew:
                return False
        return True

    def _eligible_cts(self, g: PodGroup, eligible_zones: Sequence[str]) -> List[str]:
        """Capacity-type domains this group could actually use: the cts some
        tolerable (provisioner, type, offering) admits under the merged and
        pod-level requirements, IN A ZONE the group may use (k8s semantics
        judge skew over nodeAffinity-filtered domains — a ct offered only in
        a zone the pod's selector or volume pin excludes is not a domain the
        spread can level against).  Skew is judged over reachable domains
        (the reference computes domains from the provisioners' requirement
        union, not a global constant set — a spot-only cluster must not
        strand an on-demand count at zero forever)."""
        rep = g.pods[0]
        pod_ct = g.requirements.get(L.CAPACITY_TYPE)
        zone_ok = set(eligible_zones)
        out: List[str] = []
        for _, prov, it, merged in self.pairs:
            if not prov.tolerates(rep):
                continue
            if g.requirements.intersects(merged) is not None:
                continue
            merged_ct = merged.get(L.CAPACITY_TYPE)
            merged_zone = merged.get(L.ZONE)
            for o in it.offerings:
                if (o.capacity_type not in out and o.available
                        and o.zone in zone_ok
                        and merged_zone.contains(o.zone)
                        and (it.name, o.zone, o.capacity_type) not in self.unavailable
                        and merged_ct.contains(o.capacity_type)
                        and pod_ct.contains(o.capacity_type)):
                    out.append(o.capacity_type)
        return sorted(out)

    def _host_cap(self, g: PodGroup, node: SimNode) -> float:
        """Max additional pods of g on this node from hostname-scoped rules
        (inf = unbounded)."""
        rep = g.pods[0]
        cap = float("inf")
        for tsc in rep.topology_spread:
            if not tsc.hard or tsc.topology_key != L.HOSTNAME:
                continue
            key = (tsc.label_selector, L.HOSTNAME, "spread")
            cap = min(cap, tsc.max_skew - self.topo.node[key].get(node.name, 0))
        for term in rep.affinity_terms:
            if term.topology_key != L.HOSTNAME:
                continue
            key = (term.label_selector, L.HOSTNAME, "anti" if term.anti else "affinity")
            have = self.topo.node[key].get(node.name, 0)
            if term.anti:
                if have > 0:
                    return 0.0
                # a self-matching group may put exactly one pod here
                if term.matches_pod(rep):
                    cap = min(cap, 1.0)
            else:
                if self.topo.total[key] > 0 and have == 0:
                    return 0.0
                if self.topo.total[key] == 0 and not term.matches_pod(rep):
                    return 0.0
        return cap

    def _new_node_host_cap(self, g: PodGroup) -> float:
        """Cap for pods of g on a brand-new empty node (hostname-scoped rules)."""
        rep = g.pods[0]
        cap = float("inf")
        for tsc in rep.topology_spread:
            if tsc.hard and tsc.topology_key == L.HOSTNAME:
                cap = min(cap, float(tsc.max_skew))
        for term in rep.affinity_terms:
            if term.topology_key != L.HOSTNAME:
                continue
            key = (term.label_selector, L.HOSTNAME, "anti" if term.anti else "affinity")
            if term.anti:
                if term.matches_pod(rep):
                    cap = min(cap, 1.0)
            else:
                # positive affinity: an empty node has no matching pods, so it
                # only works when the group seeds its own affinity domain
                if self.topo.total[key] > 0 or not term.matches_pod(rep):
                    return 0.0
        return cap

    # ---- main loop ------------------------------------------------------
    def run(self) -> None:
        for g in self.groups:
            self._place_group(g)

    def _group_cap(self, g: PodGroup, node: SimNode, req: ResourceList) -> int:
        """How many pods of g this node can take right now."""
        if not self._label_taint_ok(g, node):
            return 0
        rem = self._remaining(node)
        cap = float("inf")
        for k, v in req.items():
            if v > 0:
                cap = min(cap, rem.get(k, 0.0) // v)
        cap = min(cap, self._host_cap(g, node))
        return max(0, int(cap))

    def _place_group(self, g: PodGroup) -> None:
        rep = g.pods[0]
        req = dict(g.requests)
        req.setdefault(L.RESOURCE_PODS, 1.0)
        pod_reqs = g.requirements
        zone_req = pod_reqs.get(L.ZONE)
        eligible = [z for z in self.all_zones if zone_req.contains(z)]
        has_zone_rules = any(
            (t.hard and t.topology_key == L.ZONE) for t in rep.topology_spread
        ) or any(t.topology_key == L.ZONE for t in rep.affinity_terms)

        unsupported = [t.topology_key for t in rep.topology_spread
                       if t.hard and t.topology_key not in
                       (L.ZONE, L.HOSTNAME, L.CAPACITY_TYPE)]
        unsupported += [t.topology_key for t in rep.affinity_terms
                        if t.topology_key not in (L.ZONE, L.HOSTNAME)]
        if unsupported:
            # the reference supports exactly three spread topologyKeys
            # (scheduling.md:339-343) and zone/hostname (anti-)affinity —
            # silently dropping a required constraint is never acceptable
            # (a dropped anti-affinity co-locates the replicas it separates)
            for pod in g.pods:
                self.infeasible[pod.name] = (
                    f"unsupported topology key {unsupported[0]!r}")
            return

        if any(t.hard and t.topology_key == L.CAPACITY_TYPE
               for t in rep.topology_spread):
            # rare path: capacity-type spread constrains the (zone, ct)
            # domain per placement, which the per-zone heaps can't express —
            # place this group with a direct first-fit scan instead (exact
            # semantics; O(P*N) only for ct-spread groups)
            self._place_group_ct(g, req, pod_reqs, eligible, has_zone_rules)
            return

        # per-zone heaps of (creation_index, capacity_left) for open nodes
        heaps: Dict[str, list] = defaultdict(list)
        for idx, node in enumerate(self.nodes):
            cap = self._group_cap(g, node, req)
            if cap > 0:
                heapq.heappush(heaps[node.zone], [idx, cap, node])

        best_new: Dict[str, Optional[tuple]] = {}  # zone -> (score..) or None

        placed = 0
        for pod in g.pods:
            zones = [z for z in eligible if self._zone_allowed(g, z, eligible)] \
                if has_zone_rules else eligible
            # earliest-created compatible node across allowed zones (first-fit)
            chosen = None
            for z in zones:
                h = heaps.get(z)
                if h and (chosen is None or h[0][0] < chosen[0]):
                    chosen = h[0]
            if chosen is not None:
                node = chosen[2]
                self._bind(pod, node)
                chosen[1] -= 1
                if chosen[1] <= 0:
                    heapq.heappop(heaps[node.zone])
                placed += 1
                continue

            # no open node: create one
            if not self.allow_new_nodes:
                self.infeasible[pod.name] = "no existing node fits and new nodes disallowed"
                continue
            if self._new_node_host_cap(g) < 1:
                self.infeasible[pod.name] = "hostname-scoped affinity forbids a new node"
                continue
            if self.max_new_nodes is not None and len(self.new_nodes) >= self.max_new_nodes:
                self.infeasible[pod.name] = "new-node budget exhausted"
                continue
            node = self._create_node(g, req, pod_reqs, zones, g.count - placed, best_new)
            if node is None:
                self.infeasible[pod.name] = "no feasible (provisioner, instance type, offering)"
                continue
            cap = self._group_cap(g, node, req)
            self._bind(pod, node)
            placed += 1
            if cap - 1 > 0:
                heapq.heappush(heaps[node.zone], [len(self.nodes) - 1, cap - 1, node])

    def _place_group_ct(
        self, g: PodGroup, req: ResourceList, pod_reqs: Requirements,
        eligible: Sequence[str], has_zone_rules: bool,
    ) -> None:
        """Sequential placement for groups carrying a hard capacity-type
        spread: every placement re-derives the allowed (zone, ct) domains,
        first-fits the earliest-created compatible node, else creates a node
        restricted to the allowed cts.  No heaps/caches — exactness over
        speed on this rare constraint shape."""
        eligible_cts = self._eligible_cts(g, eligible)
        placed = 0
        for pod in g.pods:
            zones = ([z for z in eligible if self._zone_allowed(g, z, eligible)]
                     if has_zone_rules else list(eligible))
            cts = [c for c in eligible_cts
                   if self._ct_allowed(g, c, eligible_cts)]
            if not cts:
                self.infeasible[pod.name] = (
                    "capacity-type spread skew exhausted in every domain")
                continue
            chosen = None
            for idx, node in enumerate(self.nodes):
                if node.zone not in zones or node.capacity_type not in cts:
                    continue
                if self._group_cap(g, node, req) > 0:
                    chosen = node
                    break
            if chosen is not None:
                self._bind(pod, chosen)
                placed += 1
                continue
            if not self.allow_new_nodes:
                self.infeasible[pod.name] = (
                    "no existing node fits and new nodes disallowed")
                continue
            if self._new_node_host_cap(g) < 1:
                self.infeasible[pod.name] = (
                    "hostname-scoped affinity forbids a new node")
                continue
            if (self.max_new_nodes is not None
                    and len(self.new_nodes) >= self.max_new_nodes):
                self.infeasible[pod.name] = "new-node budget exhausted"
                continue
            # fresh best_new per pod: the allowed-ct set changes per
            # placement, so the per-zone score cache must not carry over
            node = self._create_node(g, req, pod_reqs, zones,
                                     g.count - placed, {}, allowed_cts=cts)
            if node is None:
                self.infeasible[pod.name] = (
                    "no feasible (provisioner, instance type, offering)")
                continue
            self._bind(pod, node)
            placed += 1

    def _bind(self, pod: PodSpec, node: SimNode) -> None:
        node.pods.append(pod)
        self._rem_cache.pop(id(node), None)  # remaining() changed
        self.assignments[pod.name] = node.name
        self.topo.observe(pod, node.zone, node.name, self.selectors,
                          ct=node.capacity_type)

    def _create_node(
        self,
        g: PodGroup,
        req: ResourceList,
        pod_reqs: Requirements,
        allowed_zones: Sequence[str],
        remaining: int,
        best_new: Dict[str, Optional[tuple]],
        allowed_cts: Optional[Sequence[str]] = None,
    ) -> Optional[SimNode]:
        """Pick min-score (candidate, offering) over allowed zones, create node."""
        best = None
        for z in allowed_zones:
            if z not in best_new:
                best_new[z] = self._best_in_zone(g, req, pod_reqs, z, remaining,
                                                 allowed_cts=allowed_cts)
            b = best_new[z]
            if b is not None and (best is None or b[0] < best[0]):
                best = b
        if best is None:
            return None
        _, prov, it, merged, o, eff_alloc = best

        # provisioner limits re-check at creation time (usage moved since scoring)
        if prov.limits:
            usage = self.prov_usage[prov.name]
            if any(
                usage.get(rk, 0.0) + it.capacity.get(rk, 0.0) > prov.limits[rk] + 1e-9
                for rk in prov.limits
            ):
                # invalidate zone caches that chose this provisioner and retry once
                for z in list(best_new):
                    if best_new[z] is not None and best_new[z][1] is prov:
                        best_new[z] = self._best_in_zone(
                            g, req, pod_reqs, z, remaining,
                            allowed_cts=allowed_cts)
                return self._create_node(g, req, pod_reqs, allowed_zones,
                                         remaining, best_new,
                                         allowed_cts=allowed_cts)

        labels = {**it.labels(), **prov.labels}
        for r in merged.to_list() + pod_reqs.to_list():
            if r.operator == IN and len(r.values) == 1 and r.key not in labels:
                labels[r.key] = r.values[0]
        node = SimNode(
            instance_type=it.name,
            provisioner=prov.name,
            zone=o.zone,
            capacity_type=o.capacity_type,
            price=o.price,
            allocatable=eff_alloc,
            labels=labels,
            taints=list(prov.taints),
        )
        node.labels[L.ZONE] = o.zone
        node.labels[L.CAPACITY_TYPE] = o.capacity_type
        node.labels[L.PROVISIONER_NAME] = prov.name
        node.labels[L.INSTANCE_TYPE] = it.name
        node.labels[L.HOSTNAME] = node.name
        self.nodes.append(node)
        self.new_nodes.append(node)
        self.prov_usage[prov.name] = add(
            self.prov_usage[prov.name],
            {L.RESOURCE_CPU: it.capacity.get(L.RESOURCE_CPU, 0.0),
             L.RESOURCE_MEMORY: it.capacity.get(L.RESOURCE_MEMORY, 0.0)},
        )
        return node

    def _best_in_zone(
        self, g: PodGroup, req: ResourceList, pod_reqs: Requirements,
        zone: str, remaining: int,
        allowed_cts: Optional[Sequence[str]] = None,
    ) -> Optional[tuple]:
        rep = g.pods[0]
        pod_ct = pod_reqs.get(L.CAPACITY_TYPE)
        best = None
        for ci, (pi, prov, it, merged) in enumerate(self.pairs):
            if not prov.tolerates(rep):
                continue
            if pod_reqs.intersects(merged) is not None:
                continue
            overhead = self._daemon_overhead(prov, it)
            eff_alloc = {k: v - overhead.get(k, 0.0) for k, v in it.allocatable.items()}
            if not fits(req, eff_alloc):
                continue
            if prov.limits:
                usage = self.prov_usage[prov.name]
                if any(
                    usage.get(rk, 0.0) + it.capacity.get(rk, 0.0) > prov.limits[rk] + 1e-9
                    for rk in prov.limits
                ):
                    continue
            ppn = _pods_per_node(req, eff_alloc)
            if ppn < 1:
                continue
            denom = max(1, min(ppn, remaining))
            merged_zone = merged.get(L.ZONE)
            merged_ct = merged.get(L.CAPACITY_TYPE)
            for oi, o in enumerate(it.offerings):
                if o.zone != zone:
                    continue
                if not o.available or (it.name, o.zone, o.capacity_type) in self.unavailable:
                    continue
                if not merged_zone.contains(o.zone):
                    continue
                if not (merged_ct.contains(o.capacity_type) and pod_ct.contains(o.capacity_type)):
                    continue
                if allowed_cts is not None and o.capacity_type not in allowed_cts:
                    continue  # capacity-type spread skew forbids this ct now
                score = (o.price / denom, o.price, ci, oi)
                if best is None or score < best[0]:
                    best = (score, prov, it, merged, o, eff_alloc)
        return best


def _pods_per_node(req: ResourceList, alloc: ResourceList) -> int:
    ppn = float("inf")
    for k, v in req.items():
        if v <= 0:
            continue
        ppn = min(ppn, alloc.get(k, 0.0) // v)
    return int(ppn) if ppn != float("inf") else 0


def solve(
    pods: Sequence[PodSpec],
    provisioners: Sequence[Provisioner],
    instance_types: Sequence[InstanceType],
    *,
    existing_nodes: Sequence[SimNode] = (),
    daemonsets: Sequence[PodSpec] = (),
    unavailable: Optional[Set[tuple]] = None,
    allow_new_nodes: bool = True,
    max_new_nodes: Optional[int] = None,
) -> SolveResult:
    """Run the sequential FFD pack.  ``existing_nodes`` are tried first-fit
    before any new node is proposed (provisioning hot path SURVEY §3.2 step 3;
    the consolidation what-if reuses this with ``allow_new_nodes``/
    ``max_new_nodes`` — §3.3)."""
    t0 = time.perf_counter()
    # snapshots: simulated placements must not leak into the caller's nodes
    existing = [n.snapshot() for n in existing_nodes]
    s = _Solver(
        pods, provisioners, instance_types, existing, list(daemonsets),
        unavailable or set(), allow_new_nodes, max_new_nodes,
    )
    s.run()
    return SolveResult(
        nodes=s.new_nodes,
        assignments=s.assignments,
        infeasible=s.infeasible,
        existing_nodes=existing,
        solve_ms=(time.perf_counter() - t0) * 1000.0,
    )
