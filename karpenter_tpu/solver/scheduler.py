"""Batch scheduler facade — routes pods to the TPU solver or the CPU oracle.

The provisioning and deprovisioning controllers call this, never the solvers
directly (the ``scheduling.Solve`` boundary, SURVEY.md §3.2 step 3).  Pods the
TPU path can't express (positive pod-affinity, v1 — see solver/tpu.py
docstring) are carved out and solved by the oracle against the TPU result's
node set, so one SolveResult comes back either way.
"""

from __future__ import annotations

import copy
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Set, Tuple

import logging

from ..metrics import (
    MEGABATCH_FLUSH,
    MEGABATCH_FLUSH_REASONS,
    MEGABATCH_SLOTS,
    MULTIHOST_FENCE_BYTES,
    MULTIHOST_FENCE_SCOPES,
    MULTIHOST_SLOT_OWNERSHIP,
    MULTIHOST_SLOTS,
    MULTIHOST_UNIFIED,
    PRECOMPILE_DURATION,
    SCHEDULING_DURATION,
    SOLVER_BACKEND_DURATION,
    SOLVER_COLD_FALLBACKS,
    SOLVER_COMPILE_DURATION,
    SOLVER_COMPILE_IN_PROGRESS,
    SOLVER_DEGRADED_SOLVES,
    SOLVER_DEVICE_HANGS,
    SOLVER_DEVICE_HEALTHY,
    INFLIGHT_DEPTH,
    TENSORIZE_CACHE_HITS,
    TENSORIZE_CACHE_MISSES,
    TENSORIZE_DURATION,
    Registry,
    registry as default_registry,
)
from ..models import labels as L
from ..models.instancetype import InstanceType
from ..models.pod import LabelSelector, PodSpec
from ..models.provisioner import Provisioner
from ..models.tensorize import (
    TensorizeCache,
    batch_needs_oracle,
    device_inexpressible,
    tensorize,
)
from ..obs import tracer_for
from ..obs.trace import NULL_TRACE, Tracer
from .guard import DeviceGuard, DeviceHang
from .reference import solve as oracle_solve
from .tpu import (
    MEGA_MAX_SLOTS,
    SlotsExhausted,
    TpuSolver,
    _mesh_size,
    mega_key_at_slots,
    mega_key_dims,
    mesh_shardable,
    unify_mega_keys,
)
from .types import SimNode, SolveResult

logger = logging.getLogger(__name__)


#: "auto" routes batches below this pod count (with no topology constraints)
#: to the native C++ tier; larger or constrained batches go to the device.
NATIVE_BATCH_LIMIT = 256
#: relaxation-ladder depth cap: at most this many retry waves per solve; a
#: pod with more preferences has its top rungs collapsed (several dropped at
#: once) instead of funding one solve per preference
MAX_RELAXATION_WAVES = 8
#: residue-convergence depth: still-infeasible pods re-solve against the
#: accumulated placed state until nothing more places (or this many waves).
#: This is the batched solver's equivalent of the sequential oracle's
#: invalidate-and-retry: the oracle co-packs multi-group residuals onto tail
#: nodes and cascades through limit-capped provisioners one placement at a
#: time; each wave here gives the device solve the same second look at open
#: rows and remaining limit headroom (karpenter.sh_provisioners.yaml:160-173
#: limits + :305-314 weights).
MAX_RESIDUE_WAVES = 6


def _delta_local_enabled() -> bool:
    """Meshed delta steps route through the host-local single-shard
    program by default (ISSUE 14: the sub-ms displaced-subproblem solves
    must not pay sharded dispatch + mesh fence); ``KT_DELTA_LOCAL=0``
    keeps them on the scheduler's mesh."""
    return os.environ.get("KT_DELTA_LOCAL", "1") != "0"


def _compile_behind_enabled() -> bool:
    """Measurement escape hatch: KT_COMPILE_BEHIND=0 serves cold shapes from
    the warm tier WITHOUT starting the background compile — used by the
    cold-start benchmark subprocess, which exits right after one solve and
    must not wait out a 40 s XLA compile at interpreter shutdown."""
    return os.environ.get("KT_COMPILE_BEHIND", "1") != "0"


def _soft_spreads(pod: PodSpec):
    return [t for t in pod.topology_spread if not t.hard]


def _n_preferences(pod: PodSpec) -> int:
    """Relaxable preferences: preferred node-affinity terms + ScheduleAnyway
    topology spreads (both sit on the same relaxation ladder, like core's
    Preferences — scheduling.md:205-233 + :303-346 ScheduleAnyway)."""
    return len(pod.preferred_affinity_terms) + len(_soft_spreads(pod))


def _harden_preferences(pod: PodSpec, keep: Optional[int] = None) -> PodSpec:
    """Fold the first ``keep`` preferences (all when None) into the hard
    constraint set: preferred affinity terms join the required set,
    ScheduleAnyway spreads become DoNotSchedule.  The ladder drops soft
    spreads first (they sort after affinity terms), then affinity terms
    last-first.  Returns the pod unchanged when it has no preferences."""
    if not pod.preferred_affinity_terms and (
        not pod.topology_spread or all(t.hard for t in pod.topology_spread)
    ):
        return pod  # no preferences (the hot path at scale)

    from ..models.pod import TopologySpreadConstraint

    prefs_aff = pod.preferred_affinity_terms
    soft = _soft_spreads(pod)
    total = len(prefs_aff) + len(soft)
    k = total if keep is None else max(0, keep)
    kept_aff = prefs_aff[: min(k, len(prefs_aff))]
    kept_soft = soft[: max(0, k - len(prefs_aff))]

    out = copy.copy(pod)
    if kept_aff:
        out.required_affinity_terms = [
            list(term) + [r for pt in kept_aff for r in pt]
            for term in (pod.required_affinity_terms or [[]])
        ]
    out.preferred_affinity_terms = []
    out.topology_spread = [t for t in pod.topology_spread if t.hard] + [
        TopologySpreadConstraint(t.max_skew, t.topology_key, "DoNotSchedule",
                                 t.label_selector)
        for t in kept_soft
    ]
    out.__dict__.pop("_group_key", None)  # hardened copy needs its own key
    return out


def _adopt_placed(prev_existing: List[SimNode], sub: SolveResult):
    """Split a wave's placed snapshots back into (existing, prior+new nodes).

    ``sub`` solved against ``prev_existing + <prior new nodes>`` in that
    order and returned its placed copies in ``sub.existing_nodes``; the
    copies replace the prior references so the next wave sees every
    placement so far — capacity bookkeeping chains across waves without
    mutating the caller's node objects.  The ONLY place this split-index
    logic lives; both _merge and _solve_tpu's staging use it."""
    ne = len(prev_existing)
    placed = list(sub.existing_nodes)
    return placed[:ne], placed[ne:] + list(sub.nodes)


def _merge(result: SolveResult, sub: SolveResult) -> None:
    """Fold a retry wave's outcome into ``result`` (shared by the preference
    ladder and the OR-term ladder so their merge semantics cannot diverge)."""
    for name in list(result.infeasible):
        if name in sub.assignments:
            del result.infeasible[name]
    result.infeasible.update(sub.infeasible)
    result.assignments.update(sub.assignments)
    result.existing_nodes, result.nodes = _adopt_placed(result.existing_nodes, sub)
    result.solve_ms += sub.solve_ms
    result.tensorize_ms += sub.tensorize_ms
    result.served_cold = result.served_cold or sub.served_cold


class _PendingWave:
    """A dispatched-but-unfenced first solver wave; ``finish()`` fences the
    device, handles the fallback ladders, and returns the wave's
    SolveResult.  Internal to the scheduler's submit/solve split."""

    __slots__ = ("finish",)

    def __init__(self, finish) -> None:
        self.finish = finish


class PendingScheduleResult:
    """Handle returned by :meth:`BatchScheduler.submit`; ``result()`` blocks
    on the device fence (one RTT) plus any retry epilogues and is
    idempotent."""

    __slots__ = ("_finish", "_result")

    def __init__(self, finish) -> None:
        self._finish = finish
        self._result: Optional[SolveResult] = None

    def result(self) -> SolveResult:
        if self._result is None:
            self._result = self._finish()
        return self._result


def _budget_left(result: SolveResult, max_new_nodes: Optional[int]) -> Optional[int]:
    return (None if max_new_nodes is None
            else max(0, max_new_nodes - len(result.nodes)))


class _MegaSlot:
    """One request's slot in a pending megabatch dispatch: ``result()`` is
    valid after the owning collector's ``dispatch()`` ran; it fences lazily
    (first resolver fences the whole group — the overlap window between
    megabatch N's dispatch and its fence belongs to the pipeline) and
    re-raises the slot's own exception (SlotsExhausted / DeviceHang) so the
    per-request fallback ladder in ``_solve_tpu`` stays identical to the
    single path."""

    __slots__ = ("_collector", "_idx")

    def __init__(self, collector: "_MegaCollector", idx: int) -> None:
        self._collector = collector
        self._idx = idx

    def result(self):
        return self._collector.resolve(self._idx)


class _MegaCollector:
    """Deferred cross-request device dispatch (``BatchScheduler.submit_many``).

    During the registration phase ``_solve_tpu`` routes each request's first
    device wave here instead of dispatching it; ``dispatch()`` then enqueues
    ONE vmapped device program per shape bucket (``solve_many_async``) —
    or, while a slot-rung program is still compiling behind, per-request
    async dispatches on the already-compiled single program (warming the
    rung).  Nothing fences at dispatch: the first ``resolve()`` of a group
    pays its single batch-wide fence, so the pipeline coalesces and
    tensorizes megabatch N+1 while N executes on the device.
    Single-threaded: registration, dispatch, and resolution all happen on
    the pipeline's dispatcher thread (the submit_many contract)."""

    def __init__(self, solver: TpuSolver, guard=None, registry=None,
                 warm=None, mesh=None, on_mesh_serial=None,
                 flush_reason: Optional[str] = None) -> None:
        self.solver = solver
        self.guard = guard
        self.registry = registry
        self.warm = warm
        #: the owning scheduler's device mesh: flushes dispatch the SHARDED
        #: megabatch program (slot axis over the flattened mesh) and the
        #: serial fallback dispatches the sharded single-solve program
        self.mesh = mesh
        #: scheduler hook counting/logging a meshed flush that degraded to
        #: serial dispatches (MEGABATCH_FLUSH{reason="mesh_serial"})
        self.on_mesh_serial = on_mesh_serial
        #: the pipeline's coalescer reason for this flush, or None for
        #: direct submit_many callers.  When set, the collector owns the
        #: flush count and incs exactly ONE reason at dispatch —
        #: "mesh_serial" if the meshed flush degraded to serial, else this
        #: reason — so the counter's labels stay a partition of flushes
        #: (counting upfront at the pipeline AND again on degradation
        #: would double-count every degraded meshed flush)
        self.flush_reason = flush_reason
        self._degraded = False
        self.entries: List[dict] = []
        #: per-slot resolver state after dispatch():
        #: ("mega", PendingMegaSolve, pos) | ("single", PendingTpuSolve)
        #: | ("err", Exception)
        self._slots: List[tuple] = []

    def add(self, **entry) -> _MegaSlot:
        self.entries.append(entry)
        return _MegaSlot(self, len(self.entries) - 1)

    def _observe_slots(self, occupied: int) -> None:
        if self.registry is not None:
            self.registry.histogram(MEGABATCH_SLOTS).observe(occupied)

    def _guarded(self, fn):
        return self.guard.run(fn) if self.guard else fn()

    def _mesh_serial(self, detail: str) -> None:
        if self.mesh is None:
            return
        first_degrade = not self._degraded
        self._degraded = True
        if self.on_mesh_serial is not None:
            # the counter is in FLUSH units: pipeline-owned flushes count
            # at end of dispatch() instead, and a direct caller's flush
            # counts on its FIRST degraded group only (a flush spanning
            # two cold buckets is still one degraded flush)
            self.on_mesh_serial(
                detail,
                count=self.flush_reason is None and first_degrade)

    def dispatch(self) -> None:
        self._slots = [None] * len(self.entries)
        sigs: List[tuple] = []
        groups: Dict[tuple, List[int]] = {}
        for i, e in enumerate(self.entries):
            key = self.solver.mega_signature(
                e["st"], existing_nodes=e["existing_nodes"],
                max_nodes=e["max_nodes"], slots=1, mesh=self.mesh,
            )
            sigs.append(key)
            groups.setdefault(key, []).append(i)
        # host-aware mixed-bucket unification (ISSUE 14): merge shape
        # buckets whose dims UNIFY (one dominates component-wise —
        # solver/tpu.unify_mega_keys) so the whole flush shares ONE mesh
        # dispatch at the dominant bucket's program instead of serial
        # per-bucket dispatches; dominated requests pad up via
        # target_dims, byte-identical to their own-bucket solves
        merged: List[list] = []  # [unified_key, idxs, n_source_buckets]
        for key, idxs in groups.items():
            for m in merged:
                u = unify_mega_keys(m[0], key)
                if u is not None:
                    m[0] = u
                    m[1].extend(idxs)
                    m[2] += 1
                    break
            else:
                merged.append([key, list(idxs), 1])
        for ukey, idxs, n_src in merged:
            idxs.sort()  # slot order == arrival order, like the old path
            unified = n_src > 1
            use_mega = len(idxs) > 1 and mesh_shardable(self.mesh)
            if len(idxs) > 1 and not mesh_shardable(self.mesh):
                # device count past the slot-rung ladder: this mesh cannot
                # pad a batch to one-slot-per-chip (bucket_key already
                # rejects these; direct submit_many callers land here)
                self._mesh_serial(
                    f"{_mesh_size(self.mesh)}-device mesh exceeds the "
                    f"{MEGA_MAX_SLOTS}-slot rung ladder")
            if use_mega:
                mega_sig = mega_key_at_slots(ukey, len(idxs), self.mesh)
                if not self.solver.ready(mega_sig):
                    # callers must never eat a cold compile (the compile-
                    # behind contract): serve this flush from the compiled
                    # single program, compile the slot-rung program behind.
                    # Warm from an entry OF the dominant bucket, so the
                    # compiled program is the one a unified flush runs.
                    if self.warm is not None:
                        warm_i = next(
                            (i for i in idxs if sigs[i] == ukey), idxs[0])
                        self.warm(self.entries[warm_i], len(idxs))
                    use_mega = False
                    self._mesh_serial("sharded slot-rung program still "
                                      "compiling behind")
            if use_mega:
                if unified and self.registry is not None:
                    self.registry.counter(MULTIHOST_UNIFIED).inc()
                reqs = [
                    dict(
                        st=self.entries[i]["st"],
                        existing_nodes=self.entries[i]["existing_nodes"],
                        max_nodes=self.entries[i]["max_nodes"],
                        raise_on_exhaust=self.entries[i]["raise_on_exhaust"],
                        trace=self.entries[i]["trace"],
                    )
                    for i in idxs
                ]
                target = mega_key_dims(ukey) if unified else None
                try:
                    handle = self._guarded(
                        lambda reqs=reqs, target=target:
                        self.solver.solve_many_async(
                            reqs, mesh=self.mesh, target_dims=target,
                            registry=self.registry))
                except DeviceHang as err:
                    # hang at H2D dispatch: fan to every slot — each
                    # request's _finish_mega degrades to the warm tier
                    for i in idxs:
                        self._slots[i] = ("err", err)
                    continue
                # ktlint: allow[KT005] megabatch CONSTRUCTION failures
                # (bucket mismatch after a raced warm-state flip, stacking
                # errors) degrade the flush to the proven serial path —
                # clients must never fail on an optimization-layer error
                except Exception:  # noqa: BLE001
                    logger.warning(
                        "megabatch dispatch failed; serving the flush "
                        "serially", exc_info=True)
                    self._mesh_serial("megabatch construction failed; "
                                      "flush degraded")
                    self._dispatch_serial(idxs)
                    continue
                self._observe_slots(len(idxs))
                for pos, i in enumerate(idxs):
                    self._slots[i] = ("mega", handle, pos)
            else:
                self._dispatch_serial(idxs)
        if self.flush_reason is not None and self.registry is not None:
            # pipeline-owned flush count: exactly one reason per flush
            reason = "mesh_serial" if self._degraded else self.flush_reason
            self.registry.counter(MEGABATCH_FLUSH).inc({"reason": reason})

    def _dispatch_serial(self, idxs: List[int]) -> None:
        """Per-request async dispatches on the single-solve program (the
        SHARDED single program for a meshed collector): still one enqueue
        per request before any fence (the cold-rung and degraded-flush
        path)."""
        for i in idxs:
            e = self.entries[i]
            self._observe_slots(1)
            try:
                pending = self._guarded(
                    lambda e=e: self.solver.solve_async(
                        e["st"], existing_nodes=e["existing_nodes"],
                        max_nodes=e["max_nodes"], mesh=self.mesh,
                        raise_on_exhaust=e["raise_on_exhaust"],
                        trace=e["trace"],
                    ))
            # ktlint: allow[KT005] boxed per-slot outcome, re-raised
            # by the request's own _MegaSlot.result()
            except BaseException as err:  # noqa: BLE001
                self._slots[i] = ("err", err)
                continue
            self._slots[i] = ("single", pending)

    def resolve(self, idx: int):
        """Fence-and-extract slot ``idx`` (first resolver of a mega group
        fences the whole group; later ones hit the cached outputs)."""
        state = self._slots[idx]
        assert state is not None, "megabatch slot read before dispatch()"
        if state[0] == "err":
            raise state[1]
        if state[0] == "single":
            return self._guarded(state[1].result)
        _kind, handle, pos = state
        outs = self._guarded(handle.results)
        out = outs[pos]
        if isinstance(out, BaseException):
            raise out
        return out


class BatchScheduler:
    def __init__(
        self,
        backend: str = "auto",  # "auto" | "tpu" | "native" | "oracle"
        registry: Optional[Registry] = None,
        mesh=None,
        native_batch_limit: int = NATIVE_BATCH_LIMIT,
        compile_behind: Optional[bool] = None,  # None: KT_COMPILE_BEHIND env
        tracer: Optional[Tracer] = None,
    ) -> None:
        assert backend in ("auto", "tpu", "native", "oracle")
        self.backend = backend
        self.registry = registry or default_registry
        # per-solve span tracing + anomaly dumps (obs/): callers pass a
        # Trace per solve via the `trace` kwarg; the tracer itself is held
        # for its flight recorder (hang/degraded anomaly hooks)
        self.tracer = tracer if tracer is not None else tracer_for(self.registry)
        self.mesh = mesh
        self.native_batch_limit = native_batch_limit
        self.compile_behind = (
            _compile_behind_enabled() if compile_behind is None else compile_behind
        )
        self._tpu = TpuSolver()
        # change-gated stall logging; _start_warm runs at fence time, and
        # WHICH thread fences depends on the caller (pipeline dispatcher vs
        # direct RPC threads under KT_SOLVE_PIPELINE=0) — a cheap lock makes
        # the invariant local instead of inherited from caller threading
        self._cold_lock = threading.Lock()
        self._cold_logged: Set[tuple] = set()  # guarded-by: _cold_lock
        # incremental host tensorize: group-level tensors built once per
        # batch shape, reused across solves (models/tensorize.TensorizeCache;
        # KT_TENSORIZE_CACHE=0 forces the from-scratch path for A/B runs)
        self._tensorize_cache: Optional[TensorizeCache] = (
            TensorizeCache()
            if os.environ.get("KT_TENSORIZE_CACHE", "1") != "0" else None
        )
        # hang protection for the auto policy's device dispatches (a wedged
        # TPU tunnel must degrade the reconcile loop to the warm host tiers,
        # not freeze it — see solver/guard.py); forced backends keep direct
        # calls so tests and inline-compile flows are untouched
        self._guard = DeviceGuard(on_health_change=self._device_health_changed)
        self.registry.gauge(SOLVER_DEVICE_HEALTHY).set(1)
        # zero-init so every label series exists from the first scrape (a
        # counter first appearing at its first increment loses that
        # increment to Prometheus rate()/increase()); inc(0) creates the
        # sample, merely constructing the Counter does not.  Both fallback
        # counters carry a backend label with BOTH reachable values —
        # _cold_solve returns "native" or "oracle" depending on tier
        # availability and batch topology.
        self.registry.counter(SOLVER_DEVICE_HANGS).inc(value=0.0)
        for fallback_backend in ("native", "oracle"):
            self.registry.counter(SOLVER_DEGRADED_SOLVES).inc(
                {"backend": fallback_backend}, value=0.0
            )
            self.registry.counter(SOLVER_COLD_FALLBACKS).inc(
                {"backend": fallback_backend}, value=0.0
            )
        for tier in ("identity", "shape"):
            self.registry.counter(TENSORIZE_CACHE_HITS).inc(
                {"tier": tier}, value=0.0
            )
        self.registry.counter(TENSORIZE_CACHE_MISSES).inc(value=0.0)
        # 0 in flight until a SolvePipeline drives submit(); the series must
        # exist from process start like every other solver series — but only
        # when absent: re-constructing a scheduler (per-backend lazily, or
        # in tests) must not clobber a live pipeline's depth
        inflight = self.registry.gauge(INFLIGHT_DEPTH)
        if not inflight.has({"backend": self.backend}):
            inflight.set(0, {"backend": self.backend})
        # megabatch collector: non-None only INSIDE submit_many's
        # registration phase, on the pipeline dispatcher thread — _solve_tpu
        # routes first device waves through it instead of dispatching
        self._mega_collect: Optional[_MegaCollector] = None
        # register the megabatch/precompile families so the documented
        # metrics are visible before the first megabatch lands; every flush
        # reason exists at 0 from construction (KT003 — the pipeline
        # re-zero-inits too, for facade schedulers without this init)
        self.registry.histogram(MEGABATCH_SLOTS)
        self.registry.histogram(PRECOMPILE_DURATION)
        for reason in MEGABATCH_FLUSH_REASONS:
            self.registry.counter(MEGABATCH_FLUSH).inc(
                {"reason": reason}, value=0.0)
        # multi-host serving families (ISSUE 14): per-host fence byte
        # accounting, slot-ownership demux counts, unified-flush counts —
        # all exist at 0 from construction (KT003)
        fence_c = self.registry.counter(MULTIHOST_FENCE_BYTES)
        for scope in MULTIHOST_FENCE_SCOPES:
            fence_c.inc({"scope": scope}, value=0.0)
        slots_c = self.registry.counter(MULTIHOST_SLOTS)
        for ownership in MULTIHOST_SLOT_OWNERSHIP:
            slots_c.inc({"ownership": ownership}, value=0.0)
        self.registry.counter(MULTIHOST_UNIFIED).inc(value=0.0)
        # a meshed scheduler degrading a would-be sharded megabatch to
        # serial dispatches logs once per process (the metric carries the
        # ongoing count; the log explains the first occurrence)
        self._mesh_serial_logged = False  # guarded-by: _cold_lock
        #: the unshardable-mesh verdict, hoisted to construction (ISSUE 14
        #: satellite): a mesh whose device count exceeds the slot-rung
        #: ladder can never serve a sharded megabatch, so per-request
        #: probes (bucket_key) return None immediately instead of walking
        #: the log-once path per queued request — the verdict is logged
        #: ONCE, here, where it is decided
        self.mega_unshardable = (
            mesh is not None and not mesh_shardable(mesh))
        if self.mega_unshardable and backend in ("auto", "tpu"):
            logger.info(
                "mesh of %d devices exceeds the %d-slot rung ladder: "
                "megabatching is off for this scheduler; flushes serve "
                "serially and count under karpenter_solver_megabatch_"
                "flush_total{reason=\"mesh_serial\"}",
                _mesh_size(mesh), MEGA_MAX_SLOTS)
        # warm-start delta series exist before the first solve_delta call
        from .warmstart import zero_init_metrics as _ws_zero_init

        _ws_zero_init(self.registry)
        # relax-rung series exist before the first device solve (KT003)
        from .relax import zero_init_metrics as _rx_zero_init

        _rx_zero_init(self.registry)
        # hierarchical-routing series exist before the first 100k+ batch
        from .hierarchy import zero_init_hier_metrics as _hier_zero_init

        _hier_zero_init(self.registry)
        # gang outcome series exist before the first ganged batch (KT003)
        from ..gang import zero_init_gang_metrics as _gang_zero_init

        _gang_zero_init(self.registry)
        # hierarchical re-entrancy depth: repair solves issued from inside
        # solve_hierarchical must never route hierarchically themselves
        self._hier_depth = 0

    def _device_health_changed(self, healthy: bool) -> None:
        self.registry.gauge(SOLVER_DEVICE_HEALTHY).set(1 if healthy else 0)
        if not healthy:
            self.registry.counter(SOLVER_DEVICE_HANGS).inc()

    def solve(
        self,
        pods: Sequence[PodSpec],
        provisioners: Sequence[Provisioner],
        instance_types: Sequence[InstanceType],
        *,
        existing_nodes: Sequence[SimNode] = (),
        daemonsets: Sequence[PodSpec] = (),
        unavailable: Optional[Set[tuple]] = None,
        allow_new_nodes: bool = True,
        max_new_nodes: Optional[int] = None,
        trace=None,
        relax: Optional[bool] = None,
    ) -> SolveResult:
        """Solve with preference relaxation: pods carrying preferences
        (preferred affinity terms, ScheduleAnyway topology spreads) are first
        solved with all preferences hardened; any that come back infeasible
        retry dropping one preference at a time, last first (the reference's
        scheduler relaxes preferences one failure at a time —
        scheduling.md:205-233).  Pods with OR'd required-affinity terms
        that stay infeasible under term[0] retry under each alternate term —
        with the full preference ladder re-applied per term, so a pod landing
        on term[1] still honors its satisfiable preferences."""
        return self._submit(
            pods, provisioners, instance_types,
            existing_nodes=existing_nodes, daemonsets=daemonsets,
            unavailable=unavailable, allow_new_nodes=allow_new_nodes,
            max_new_nodes=max_new_nodes, trace=trace, relax=relax,
            # a synchronous caller fences immediately — async dispatch buys
            # no overlap and would just split the device call across two
            # code paths; keep solve() on the classic sync path
            dispatch=False,
        ).result()

    def submit(
        self,
        pods: Sequence[PodSpec],
        provisioners: Sequence[Provisioner],
        instance_types: Sequence[InstanceType],
        *,
        existing_nodes: Sequence[SimNode] = (),
        daemonsets: Sequence[PodSpec] = (),
        unavailable: Optional[Set[tuple]] = None,
        allow_new_nodes: bool = True,
        max_new_nodes: Optional[int] = None,
        trace=None,
        relax: Optional[bool] = None,
    ) -> "PendingScheduleResult":
        """Async entry point for pipelined callers (service/server.py
        SolvePipeline): tensorizes and DISPATCHES the first solver wave to
        the device, then returns a handle whose ``result()`` fences and runs
        the (usually zero-iteration) relaxation/residue epilogues.  Between
        ``submit`` and ``result`` the host is free — the pipeline tensorizes
        batch N+1 there while batch N executes on the device.  Same
        result semantics as :meth:`solve`.
        Not re-entrant: submits and results must come from one thread, and
        results must be taken in submit order (FIFO) — the solver waves
        chain per-call state only, so interleaved independent batches are
        safe, concurrent ones are not."""
        return self._submit(
            pods, provisioners, instance_types,
            existing_nodes=existing_nodes, daemonsets=daemonsets,
            unavailable=unavailable, allow_new_nodes=allow_new_nodes,
            max_new_nodes=max_new_nodes, trace=trace, relax=relax,
            dispatch=True,
        )

    def solve_delta(
        self,
        prev: SolveResult,
        added: Sequence[PodSpec] = (),
        removed: Sequence[str] = (),
        iced: Sequence[object] = (),
        *,
        provisioners: Sequence[Provisioner],
        instance_types: Sequence[InstanceType],
        daemonsets: Sequence[PodSpec] = (),
        unavailable: Optional[Set[tuple]] = None,
        max_delta_frac: Optional[float] = None,
        force_full: bool = False,
        trace=None,
    ):
        """Warm-start delta solve through the full scheduler ladder (see
        solver/warmstart.py): removals and unconstrained adds are host
        bookkeeping; displaced pods that need a real solve go through
        :meth:`solve` seeded with the surviving placements — preference
        relaxation, oracle carve-outs, residue waves and the auto-policy
        routing all apply to the subproblem exactly as they would to a
        fresh batch.  Falls back to a full :meth:`solve` of the whole pod
        set when the perturbation exceeds ``KT_DELTA_MAX_FRAC`` or a
        coupling guard trips.  Consumes ``prev``; returns a
        ``DeltaOutcome``."""
        from . import warmstart
        from .relax import relax_delta_enabled

        # the relax rung is a $-for-latency trade the sub-ms delta path
        # must not pay: displaced-subproblem scans always skip it, and the
        # FULL-solve boundaries (threshold/guard fallbacks — already
        # paying a whole re-solve) run it only when KT_RELAX_DELTA=1.
        # A MESHED scheduler's displaced subproblems route through the
        # host-local single-shard program (ISSUE 14, KT_DELTA_LOCAL):
        # these are sub-ms steps that fit one chip — the sharded program
        # would pay cross-host dispatch and a mesh-wide fence per step,
        # which is exactly the transfer tax the delta path exists to
        # avoid.  The FULL-solve fallbacks keep the mesh: a whole-cluster
        # re-solve is the workload the sharded program is built for.
        use_local = self.mesh is not None and _delta_local_enabled()

        def _solve(pods, existing, unavail, relax=False):
            if use_local:
                with self._host_local():
                    return self.solve(
                        pods, provisioners, instance_types,
                        existing_nodes=existing, daemonsets=daemonsets,
                        unavailable=unavail or None, trace=trace,
                        relax=relax,
                    )
            return self.solve(
                pods, provisioners, instance_types,
                existing_nodes=existing, daemonsets=daemonsets,
                unavailable=unavail or None, trace=trace, relax=relax,
            )

        def _solve_full(pods, existing, unavail):
            return self.solve(
                pods, provisioners, instance_types,
                existing_nodes=existing, daemonsets=daemonsets,
                unavailable=unavail or None, trace=trace,
                relax=None if relax_delta_enabled() else False,
            )

        # gang composition (ISSUE 20, docs/GANGS.md): a member removal
        # retracts the WHOLE gang — seated comembers join the removal set
        # and surface as unplaced with the typed GangUnplaced reason
        from .. import gang as gangmod

        gang_retracted: Dict[str, str] = {}
        if gangmod.gang_enabled() and removed:
            removed, gang_retracted = gangmod.expand_gang_removals(
                prev, removed)

        out = warmstart.delta_solve(
            prev, added, removed, iced,
            solve_displaced=_solve, solve_full=_solve_full,
            max_delta_frac=max_delta_frac, registry=self.registry,
            unavailable=unavailable, force_full=force_full,
        )
        # a gang add places atomically or falls back to the FULL solve:
        # when the incremental tier left an added gang (wholly, post-
        # epilogue) unplaced, re-solve everything from the stripped base —
        # one more chance before the typed verdict stands.  The warm-start
        # retention dict re-offers the failed members to the full solve.
        if (gangmod.gang_enabled() and out.mode != "full" and added
                and gangmod.delta_needs_full(out.result, added)):
            out = warmstart.delta_solve(
                out.result, (), (), (),
                solve_displaced=_solve, solve_full=_solve_full,
                max_delta_frac=max_delta_frac, registry=self.registry,
                unavailable=unavailable, force_full=True,
            )
        for name, reason in gang_retracted.items():
            out.result.infeasible.setdefault(name, reason)
        return out

    #: capability probe for SolvePipeline._flush: this scheduler's
    #: submit_many accepts flush_reason= and owns the MEGABATCH_FLUSH
    #: count for the flush (facades/test doubles without it keep the
    #: pipeline-side upfront count)
    counts_flush_reason = True

    def submit_many(
        self, requests: Sequence[dict],
        flush_reason: Optional[str] = None,
    ) -> List["PendingScheduleResult"]:
        """Cross-request megabatch entry (service/server.py SolvePipeline's
        coalescer flushes here): each request is a kwargs dict (``pods``,
        ``provisioners``, ``instance_types`` plus the :meth:`solve`
        keywords).  The registration phase runs each request's tensorize +
        routing exactly like :meth:`submit`, but first device waves land in
        a :class:`_MegaCollector` instead of dispatching; one vmapped device
        call per shape bucket then solves every slot in a single round trip.
        Returns per-request handles IN ORDER — ``result()`` runs that
        request's own epilogues (relaxation ladder, residue waves, reseat)
        against its own result only; requests share nothing but the device
        dispatch.  Same single-thread contract as :meth:`submit`.
        ``flush_reason`` (the pipeline's coalescer reason) transfers the
        MEGABATCH_FLUSH count here: the flush incs exactly one reason —
        "mesh_serial" when a meshed flush degraded to serial, else
        ``flush_reason`` — keeping the labels a partition of flushes."""
        guarded = self.backend == "auto" and self._guard.enabled
        collector = _MegaCollector(
            self._tpu, guard=self._guard if guarded else None,
            registry=self.registry, warm=self._warm_mega, mesh=self.mesh,
            on_mesh_serial=self._note_mesh_serial,
            flush_reason=flush_reason,
        )
        self._mega_collect = collector
        try:
            pendings = [
                self._submit(
                    req["pods"], req["provisioners"], req["instance_types"],
                    **{k: v for k, v in req.items()
                       if k not in ("pods", "provisioners", "instance_types",
                                    "relax")},
                    # megabatch slots skip the relax rung: the coalesced
                    # flush is the latency path, and a per-slot host
                    # rounding pass on the dispatcher thread would stall
                    # every batchmate behind it (KT_RELAX's routing note)
                    relax=bool(req.get("relax", False)),
                    dispatch=True,
                )
                for req in requests
            ]
        finally:
            self._mega_collect = None
        collector.dispatch()
        return pendings

    def _note_mesh_serial(self, detail: str, count: bool = True) -> None:
        """A mesh-configured scheduler served (or will serve) a would-be
        sharded megabatch serially: count it so meshed-serving degradation
        is visible (the acceptance dashboards watch this stay near zero),
        log the first occurrence with the why.  ``count=False`` logs only —
        used when the count is owned elsewhere: a pipeline-owned
        submit_many flush (flush_reason=) counts once at collector
        dispatch — counting here too would double-count and mix units
        with the per-flush full/deadline/bucket reasons.  (The old
        per-request caller — bucket_key probing an unshardable mesh — is
        gone: that verdict is hoisted onto ``mega_unshardable`` at
        construction, so this now only runs at flush dispatch.)"""
        if count:
            self.registry.counter(MEGABATCH_FLUSH).inc(
                {"reason": "mesh_serial"})
        with self._cold_lock:
            first = not self._mesh_serial_logged
            self._mesh_serial_logged = True
        if first:
            logger.info(
                "meshed scheduler served a megabatch flush serially (%s); "
                "counted under karpenter_solver_megabatch_flush_total"
                "{reason=\"mesh_serial\"}", detail)

    def unify_buckets(self, held_key: tuple,
                      new_key: tuple) -> Optional[tuple]:
        """Mixed-bucket unification hook for the pipeline's SlotCoalescer
        (ISSUE 14): the DOMINANT of two megabatch bucket keys when one
        subsumes the other (solver/tpu.unify_mega_keys), else None.  A
        held flush can then admit a dominated request and the whole batch
        shares one mesh dispatch at the dominant bucket's program —
        dominated requests pad up at dispatch (target_dims), results
        byte-identical to their own-bucket solves."""
        return unify_mega_keys(held_key, new_key)

    @contextmanager
    def _host_local(self):
        """Scoped mesh override: the enclosed solve waves run the
        HOST-LOCAL single-shard programs (mesh=None) instead of the
        scheduler's mesh — the delta fast path's route for sub-ms
        displaced-subproblem steps on a meshed scheduler.  Safe under the
        scheduler's documented single-dispatcher contract (the thread
        that runs submit/solve/solve_delta owns every solve section —
        concurrent solves were never allowed); readiness probes and
        compile-behind warms inside the scope target the host-local
        programs, so the first local step rides the warm host tier while
        its single-shard program compiles behind, like any cold shape."""
        prev, self.mesh = self.mesh, None
        try:
            yield
        finally:
            self.mesh = prev

    def bucket_key(self, kwargs: dict) -> Optional[tuple]:
        """Megabatch shape bucket of one queued solve request, or None when
        it cannot ride a megabatch (non-device backend, oracle routing,
        device carve-outs, cold shape, unhealthy device, cache disabled,
        or a mesh whose device count exceeds the slot-rung ladder).
        Meshed schedulers bucket like single-device ones since the sharded
        megabatch round — the key carries the mesh signature, so requests
        against different meshes can never coalesce.
        Pipeline-dispatcher-only, like submit: the tensorize it performs
        lands in the cache, so the real solve's tensorize is a hit."""
        if self.backend not in ("auto", "tpu"):
            return None
        if self.mega_unshardable:
            # the slot axis cannot pad to one-slot-per-chip on this mesh —
            # a verdict hoisted to (and logged at) construction, so the
            # per-request probe is one attribute read; the pipeline counts
            # the resulting single-request flushes under mesh_serial
            return None
        if self._tensorize_cache is None:
            return None  # bucketing leans on cached tensorize; without it
            # the probe would pay a full host build per queued request
        pods = list(kwargs.get("pods") or ())
        if not pods or not kwargs.get("allow_new_nodes", True):
            return None
        if self._route_small(len(pods)):
            return None
        try:
            hardened = [_harden_preferences(p) for p in pods]
            if batch_needs_oracle(hardened):
                return None
            if any(device_inexpressible(p) for p in hardened):
                return None  # oracle carve-outs couple waves; keep serial
            if (self.backend == "auto" and self._guard.enabled
                    and not self._guard.healthy):
                return None
            tpu_pods = hardened
            st, _tier = self._tensorize_cache.tensorize(
                tpu_pods, kwargs["provisioners"], kwargs["instance_types"],
                daemonsets=kwargs.get("daemonsets") or (),
                unavailable=kwargs.get("unavailable"),
            )
            existing = list(kwargs.get("existing_nodes") or ())
            max_new = kwargs.get("max_new_nodes")
            new_budget = len(tpu_pods) if max_new is None else max_new
            max_slots = len(existing) + new_budget
            if not self._device_ready(st, existing, max_slots):
                return None  # cold shapes keep the compile-behind path
            return self._tpu.mega_signature(
                st, existing_nodes=existing, max_nodes=max_slots, slots=1,
                mesh=self.mesh,
            )
        # ktlint: allow[KT005] the bucket probe must never fail a request —
        # an unbucketable request just solves on the classic single path,
        # where a real error surfaces with full context
        except Exception:
            logger.debug("bucket_key probe failed; request rides the single "
                         "path", exc_info=True)
            return None

    def _warm_mega(self, entry: dict, slots: int) -> None:
        """Background-compile the megabatch program for a bucket whose flush
        just fell back to serial dispatches (cold slot rung) — the SHARDED
        rung program for a meshed scheduler."""
        if not self.compile_behind or not self._guard.healthy:
            return
        started = self._tpu.warm_async(
            entry["st"],
            existing_nodes=[n.snapshot() for n in entry["existing_nodes"]],
            max_nodes=entry["max_nodes"], slots=max(2, slots),
            mesh=self.mesh, on_done=self._warm_done,
        )
        if started:
            self.registry.gauge(SOLVER_COMPILE_IN_PROGRESS).set(
                self._tpu.compiles_in_flight()
            )

    def _submit(
        self,
        pods: Sequence[PodSpec],
        provisioners: Sequence[Provisioner],
        instance_types: Sequence[InstanceType],
        *,
        existing_nodes: Sequence[SimNode] = (),
        daemonsets: Sequence[PodSpec] = (),
        unavailable: Optional[Set[tuple]] = None,
        allow_new_nodes: bool = True,
        max_new_nodes: Optional[int] = None,
        trace=None,
        relax: Optional[bool] = None,
        dispatch: bool = False,
    ) -> "PendingScheduleResult":
        t0 = time.perf_counter()
        trace = trace or NULL_TRACE
        trace.annotate(backend=self.backend, n_pods=len(pods))
        hardened = [_harden_preferences(p) for p in pods]
        try:
            # the dispatch span covers tensorize + H2D + device enqueue on
            # the async path; on the sync/oracle path it covers the whole
            # first wave (there is no separate fence to split out)
            with trace.span("dispatch", async_dispatch=dispatch):
                first = self._solve_once(
                    hardened, provisioners,
                    instance_types, list(existing_nodes), daemonsets,
                    unavailable, allow_new_nodes, max_new_nodes,
                    dispatch=dispatch, trace=trace,
                )
        except BaseException:
            # the old solve() observed in a finally around the WHOLE solve;
            # a synchronous failure before the finish closure exists must
            # still land in the histogram
            self.registry.histogram(SCHEDULING_DURATION).observe(
                time.perf_counter() - t0)
            raise

        def _finish() -> SolveResult:
            try:
                if isinstance(first, _PendingWave):
                    # the overlap window closes here: one RTT to the device
                    # fence (plus any slot-exhaustion retry) — the span that
                    # explains a solve stuck behind a wedged tunnel
                    with trace.span("fence"):
                        res0 = first.finish()
                else:
                    res0 = first
                result = self._solve_wave(
                    pods, provisioners, instance_types, list(existing_nodes),
                    daemonsets, unavailable, allow_new_nodes, max_new_nodes,
                    first=res0, trace=trace,
                )

                # the post-fence repair epilogues (OR-term ladder, residue
                # convergence, capped-node reseat) share one "reseat" span —
                # zero-iteration in steady state, the whole story when a
                # solve is slow because its batch needed repair waves
                with trace.span("reseat") as reseat_span:
                    waves = 0
                    # OR'd required-affinity terms beyond the first: the
                    # solvers pack under term[0] only (tensorize.group_pods),
                    # so still-infeasible pods retry under each alternate
                    # term in order — the term list is a disjunction
                    # (scheduling.md nodeSelectorTerms semantics).
                    max_terms = max(
                        (len(p.required_affinity_terms) for p in pods), default=0)
                    for k in range(1, max_terms):
                        alts = []
                        for p in pods:
                            if p.name in result.infeasible and len(p.required_affinity_terms) > k:
                                q = copy.copy(p)
                                q.required_affinity_terms = [p.required_affinity_terms[k]]
                                q.__dict__.pop("_group_key", None)
                                alts.append(q)
                        if not alts:
                            break
                        waves += 1
                        _merge(result, self._solve_wave(
                            alts, provisioners, instance_types,
                            list(result.existing_nodes) + result.nodes, daemonsets,
                            unavailable, allow_new_nodes,
                            _budget_left(result, max_new_nodes), trace=trace,
                        ))

                    # residue convergence (see MAX_RESIDUE_WAVES): re-offer
                    # the still-infeasible pods the state every prior wave
                    # produced — open rows on placed nodes and the limit
                    # headroom left after funded creations — until a wave
                    # places nothing new.
                    for _ in range(MAX_RESIDUE_WAVES):
                        retry = [p for p in pods if p.name in result.infeasible]
                        if not retry:
                            break
                        sub = self._solve_wave(
                            retry, provisioners, instance_types,
                            list(result.existing_nodes) + result.nodes, daemonsets,
                            unavailable, allow_new_nodes,
                            _budget_left(result, max_new_nodes), trace=trace,
                        )
                        if not sub.assignments:
                            break  # no progress: the residue is genuinely infeasible
                        waves += 1
                        _merge(result, sub)
                    # ct-spread batches are already fully oracle-interleaved
                    # (batch_needs_oracle routing); the reseat epilogue buys
                    # nothing there and its incremental _ct_allowed re-fill has
                    # the same mid-band-hole weakness the zone check guards
                    # (ADVICE r5 medium) — skip it wholesale.  Judged on the
                    # HARDENED pods: routing hardens first, so a ScheduleAnyway
                    # ct spread becomes DoNotSchedule and oracle-routes exactly
                    # like a hard one — the skip must see the same batch
                    if not batch_needs_oracle(hardened):
                        self._reseat_capped(
                            result, provisioners, instance_types, daemonsets,
                            unavailable, n_pods=len(pods),
                            max_new_nodes=max_new_nodes,
                        )
                    reseat_span.annotate(repair_waves=waves)

                # convex-relaxation refinement rung (solver/relax.py):
                # re-pack the large unconstrained groups globally and ship
                # min(scan, relax+round) — never worse by construction
                result = self._maybe_relax(
                    result, hardened, provisioners, instance_types,
                    daemonsets, unavailable, allow_new_nodes,
                    max_new_nodes, relax, trace,
                )

                # gang all-or-nothing + co-location epilogue (ISSUE 20,
                # karpenter_tpu/gang/): after the relax rung — gang groups
                # are relax-INELIGIBLE (relax.eligible_partition), so their
                # scan seats are fixed boundary conditions by the time the
                # epilogue audits, retracts, and packs them
                from .. import gang as gangmod

                if gangmod.gang_enabled() and gangmod.has_gangs(pods):
                    with trace.span("gang") as gang_span:
                        result = gangmod.run_epilogue(
                            result, pods,
                            registry=self.registry,
                            # a retraction that would disturb watched spread/
                            # affinity accounting re-solves the keep-set from
                            # the pristine pre-solve existing nodes
                            resolve=lambda keep: self._solve_wave(
                                keep, provisioners, instance_types,
                                list(existing_nodes), daemonsets, unavailable,
                                allow_new_nodes, max_new_nodes, trace=trace),
                            provisioners=provisioners,
                            instance_types=instance_types,
                            daemonsets=daemonsets,
                            unavailable=unavailable,
                            allow_new_nodes=allow_new_nodes,
                            max_new_nodes=max_new_nodes,
                            in_band=self._reseat_in_band,
                            trace=gang_span,
                        )

                trace.annotate(
                    served_cold=result.served_cold,
                    n_nodes=len(result.nodes),
                    n_infeasible=len(result.infeasible),
                    cost=round(result.new_node_cost, 4),
                    solve_ms=round(result.solve_ms, 3),
                )
                return result
            finally:
                self.registry.histogram(SCHEDULING_DURATION).observe(
                    time.perf_counter() - t0)

        return PendingScheduleResult(_finish)

    def _reseat_capped(
        self, result: SolveResult, provisioners, instance_types, daemonsets,
        unavailable, *, n_pods: int, max_new_nodes: Optional[int] = None,
    ) -> None:
        """Cost-decreasing epilogue for nearly-empty residue nodes: the scan
        solver places group-at-a-time, so a group tail (or a per-node-capped
        group — hostname anti-affinity, spread caps) can buy dedicated
        near-empty nodes where the oracle's pod-interleaved first-fit seats
        the same pods on other groups' open capacity, or serves them from a
        cheaper right-sized node (fuzz seed 5: 7 single-pod m5.large at
        +3.3%; kubelet seed 20: a zone-spread band-top orphan riding a
        2xlarge it shares with one hostname-spread pod, where re-solving
        seats the orphan on another zone's slack and downsizes the node).
        Take the new nodes holding at most two pods, re-solve exactly those
        pods with the oracle against everything else placed, and adopt the
        answer only when every pod still places AND it is strictly cheaper —
        quality can only improve by construction.  Device backends only —
        the oracle backend (and auto's oracle-served small batches) already
        interleave."""
        if (self.backend == "oracle" or self._route_small(n_pods)
                or not result.nodes or result.served_cold):
            return

        def _capped(p: PodSpec) -> bool:
            # per-node CAPS: hostname anti-affinity and hard hostname spread
            # — the shapes whose reseat wins are structural (they build
            # single-pod fleets with backfillable slack)
            return any(
                t.anti and t.topology_key == L.HOSTNAME
                for t in p.affinity_terms
            ) or any(
                t.hard and t.topology_key == L.HOSTNAME
                for t in p.topology_spread
            )

        waste = [n for n in result.nodes if n.pods and len(n.pods) <= 2]
        # bounded epilogue: a batch whose pods are node-sized (1-2 per node
        # by design) would otherwise re-solve nearly everything through the
        # sequential oracle and erase the device speedup.  Trim to a 64-pod
        # re-solve budget, keeping capped fleets first (the structural wins)
        # then the most expensive residue — never skip wholesale
        if sum(len(n.pods) for n in waste) > 64:
            waste.sort(key=lambda n: (
                0 if all(_capped(p) for p in n.pods) else 1, -n.price, n.name))
            trimmed, tot = [], 0
            for n in waste:
                if tot + len(n.pods) > 64:
                    continue  # overfull node; later smaller ones may still fit
                trimmed.append(n)
                tot += len(n.pods)
            waste = trimmed
        if not waste:
            return
        waste_ids = {id(n) for n in waste}
        waste_pods = [p for n in waste for p in n.pods]
        keep = [n for n in result.nodes if id(n) not in waste_ids]
        others = list(result.existing_nodes) + keep
        # fast screen before paying a sequential oracle solve on EVERY batch
        # whose pod count isn't a multiple of node capacity (almost all):
        # a win requires either free room for a waste pod somewhere else
        # (resource-only — caps/zones may still block, the oracle decides)
        # or a waste node that isn't the cheapest catalog way to host its
        # own pods.  A routine right-sized tail node fails both and skips.
        if not self._reseat_plausible(waste, others, instance_types):
            return
        # honor the caller's new-node budget: the epilogue may only spend
        # what the waste nodes gave back (max_new_nodes=1 what-ifs must not
        # come back with 2 replacements)
        budget = (None if max_new_nodes is None
                  else max(0, max_new_nodes - len(keep)))
        re = oracle_solve(
            waste_pods, provisioners, instance_types,
            existing_nodes=others, daemonsets=daemonsets,
            unavailable=unavailable, allow_new_nodes=True,
            max_new_nodes=budget,
        )
        old_cost = sum(n.price for n in waste)
        if re.infeasible or re.new_node_cost >= old_cost - 1e-9:
            return
        if not self._reseat_in_band(waste_pods, re, instance_types):
            return
        placed = list(re.existing_nodes)  # snapshots of others, pods seated
        ne = len(result.existing_nodes)
        result.existing_nodes = placed[:ne]
        result.nodes = placed[ne:] + list(re.nodes)
        result.assignments.update(re.assignments)

    @staticmethod
    def _reseat_plausible(waste, others, instance_types) -> bool:
        """Cheap necessary condition for a reseat win: some waste pod has
        resource-level room on another placed node (absorption might be
        possible), or some waste node is priced above the cheapest catalog
        type that fits its pods (downsizing might be possible)."""
        for n in waste:
            for p in n.pods:
                req = dict(p.requests)
                req.setdefault(L.RESOURCE_PODS, 1.0)
                for o in others:
                    rem = o.remaining()
                    if all(rem.get(k, 0.0) >= v - 1e-9 for k, v in req.items()):
                        return True
        for n in waste:
            total: Dict[str, float] = {}
            for p in n.pods:
                for k, v in p.requests.items():
                    total[k] = total.get(k, 0.0) + v
            total[L.RESOURCE_PODS] = float(len(n.pods))
            for it in instance_types:
                if not all(it.allocatable.get(k, 0.0) >= v - 1e-9
                           for k, v in total.items()):
                    continue
                cheapest = min(
                    (o.price for o in it.offerings if o.available),
                    default=None,
                )
                if cheapest is not None and cheapest < n.price - 1e-9:
                    return True
        return False

    @staticmethod
    def _reseat_in_band(moved, re, instance_types) -> bool:
        """Global zone-spread check on a reseat adoption candidate.

        The oracle's incremental band check (`counts[z]+1-min <= skew`)
        assumes an IN-BAND starting state; removing the waste nodes can hand
        it a mid-band hole it then legally over-fills from (fuzz seed 17:
        removing four 2-pod zone-1b nodes left {11,1,8}; per-placement-legal
        refilling ended {11,7,10} — skew 4 over a 3 band).  Re-check every
        moved pod's hard zone spread GLOBALLY over its eligible zones and
        reject the adoption on any violation — the pre-reseat result was
        valid, so rejecting preserves validity."""
        # spec key mirrors the ground-truth validator: same selector + skew
        # but different node pins are DIFFERENT spread groups with different
        # eligible-zone sets — deduping on (selector, skew) alone would let
        # a zone-pinned pod (trivially in band over its one zone) mask an
        # unpinned group's violation.  Specs come from EVERY pod in the
        # adoption candidate whose selector matches a moved pod, not just
        # the moved pods' own constraints — a kept group's spread counts
        # the moved pod too (the oracle's observe() matches by selector,
        # regardless of which pod carries the constraint)
        nodes = list(re.existing_nodes) + list(re.nodes)
        moved_labels = [p.labels for p in moved]
        specs = {}
        for n in nodes:
            for q in n.pods:
                for tsc in q.topology_spread:
                    if not (tsc.hard and tsc.topology_key == L.ZONE):
                        continue
                    if not any(tsc.label_selector.matches(lb)
                               for lb in moved_labels):
                        continue
                    key = (tsc.label_selector, tsc.max_skew,
                           tuple(sorted(q.node_selector.items())),
                           tuple(q.volume_zone_requirements))
                    specs.setdefault(key, (tsc, q))
        if specs:
            all_zones: List[str] = []
            for it in instance_types:
                for o in it.offerings:
                    if o.zone not in all_zones:
                        all_zones.append(o.zone)
            for tsc, rep in specs.values():
                eligible = [
                    z for z in all_zones
                    if rep.node_selector.get(L.ZONE, z) == z
                    and all(r.value_set().contains(z)
                            for r in rep.volume_zone_requirements)
                ]
                if not eligible:
                    continue
                counts = {z: 0 for z in eligible}
                for n in nodes:
                    if n.zone in counts:
                        counts[n.zone] += sum(
                            1 for q in n.pods
                            if tsc.label_selector.matches(q.labels)
                        )
                if max(counts.values()) - min(counts.values()) > tsc.max_skew:
                    return False
        # hostname anti-affinity is enforced by the oracle only for the
        # INCOMING pod's own terms; a moved pod with no terms could land
        # beside a kept pod whose anti selector matches it.  Re-check every
        # node that received a moved pod bidirectionally (the validator's
        # rule: a pod's hostname-anti term may match at most one co-located
        # pod — itself)
        moved_names = {p.name for p in moved}
        for n in nodes:
            if not any(q.name in moved_names for q in n.pods):
                continue
            for q in n.pods:
                for term in q.affinity_terms:
                    if term.anti and term.topology_key == L.HOSTNAME:
                        matches = sum(
                            1 for r in n.pods
                            if term.label_selector.matches(r.labels)
                        )
                        if matches > 1:
                            return False
        # same bidirectional rule at zone scope: any pod in a zone that
        # received a moved pod may carry a zone anti-affinity term the
        # moved pod violates (at most one matching pod — itself — in the
        # zone)
        moved_zones = {n.zone for n in nodes
                       if any(q.name in moved_names for q in n.pods)}
        for z in moved_zones:
            zone_pods = [q for n in nodes if n.zone == z for q in n.pods]
            for q in zone_pods:
                for term in q.affinity_terms:
                    if term.anti and term.topology_key == L.ZONE:
                        matches = sum(
                            1 for r in zone_pods
                            if term.label_selector.matches(r.labels)
                        )
                        allowed = 1 if term.label_selector.matches(q.labels) else 0
                        if matches > allowed:
                            return False
        # kept pods' POSITIVE zone-affinity toward moved pods: a kept pod
        # whose only selector-matching zone-mate was a moved pod is orphaned
        # when the reseat moves that pod to another zone.  Conservative
        # global re-check (rejecting keeps the valid pre-reseat result):
        # every pod carrying a positive zone term whose selector matches any
        # moved pod must still have a matching pod in its own zone — itself
        # only when no matcher exists anywhere else (the mode-B seed shape).
        for n in nodes:
            for q in n.pods:
                for term in q.affinity_terms:
                    if term.anti or term.topology_key != L.ZONE:
                        continue
                    if not any(term.label_selector.matches(lb)
                               for lb in moved_labels):
                        continue  # the reseat moved nothing this term matches
                    if any(term.label_selector.matches(r.labels)
                           for nn in nodes if nn.zone == n.zone
                           for r in nn.pods if r.name != q.name):
                        continue
                    if term.label_selector.matches(q.labels) and not any(
                        term.label_selector.matches(r.labels)
                        for nn in nodes if nn.zone != n.zone
                        for r in nn.pods
                    ):
                        continue  # sole matcher anywhere: valid self-seed
                    return False
        # hard hostname spread on nodes that RECEIVED a moved pod: the
        # oracle enforces the incoming pod's own constraints only, so a
        # moved pod landing beside a kept spread-bearing pod can push that
        # node's matching count past the band (per-node cap is maxSkew —
        # an empty node keeps the global hostname minimum at 0)
        for n in nodes:
            if not any(q.name in moved_names for q in n.pods):
                continue
            for q in n.pods:
                for tsc in q.topology_spread:
                    if not (tsc.hard and tsc.topology_key == L.HOSTNAME):
                        continue
                    matches = sum(1 for r in n.pods
                                  if tsc.label_selector.matches(r.labels))
                    if matches > tsc.max_skew:
                        return False
        return True

    def _maybe_relax(
        self, result: SolveResult, hardened, provisioners, instance_types,
        daemonsets, unavailable, allow_new_nodes,
        max_new_nodes: Optional[int], relax: Optional[bool], trace,
    ) -> SolveResult:
        """Route a finished device-tier solve through the convex-relaxation
        refinement rung (solver/relax.py) and ship min(scan, relax+round).

        ``relax`` is the caller's policy: False skips unconditionally (the
        delta fast path, megabatch slots), None defers to ``KT_RELAX``
        (default on).  The rung only applies to device-scan results — the
        oracle-routed small/ct-spread batches and forced non-device
        backends return untouched and uncounted (the rung's outcome
        counter partitions rung EVALUATIONS, not all solves) — and only to
        unbudgeted provisioning solves: consolidation what-ifs
        (max_new_nodes / allow_new_nodes) are judged on feasibility at a
        fixed budget, not on node cost.  A still-compiling relax program
        counts 'skipped' and warms behind — the serving path never eats
        the XLA stall (the compile-behind contract, KT014-audited)."""
        from . import relax as relax_mod

        if relax is False or not relax_mod.relax_enabled():
            return result
        if self.backend not in ("auto", "tpu"):
            return result  # the rung refines the device scan only
        if not allow_new_nodes or max_new_nodes is not None:
            return result
        tpu_pods = [p for p in hardened if not device_inexpressible(p)]
        if (not tpu_pods or len(tpu_pods) <= self.native_batch_limit
                or batch_needs_oracle(hardened)):
            # small batches are oracle-grade already (and under auto the
            # oracle served them — no scan to refine); the rung targets
            # LARGE unconstrained groups on every backend, so forced-tpu
            # small-batch tests/fuzz keep byte-stable scan results
            return result
        if self._tensorize_cache is None:
            return result  # without cached tensorize the probe would pay
            # a full host build per solve — not the rung's trade
        guarded = self.backend == "auto" and self._guard.enabled
        if result.served_cold or (guarded and not self._guard.healthy):
            relax_mod.record_outcome(self.registry, "skipped")
            return result
        try:
            # identity-tier hit: these are the same pod objects the solve
            # wave tensorized moments ago
            st, _tsec = self._tensorize(
                tpu_pods, provisioners, instance_types, daemonsets,
                unavailable, trace=trace)
            sig = relax_mod.relax_signature(st)
            if not self._tpu.ready(sig):
                if self.compile_behind and self._guard.healthy:
                    relax_mod.warm_relax(self._tpu, st)
                relax_mod.record_outcome(self.registry, "skipped")
                return result

            def _repair(stranded, seeds):
                # integrality repair: the existing scan, seeded from the
                # rounded fleet as existing-node state (PR-6 shape); the
                # repair solve must never re-enter the rung
                return self._submit(
                    stranded, provisioners, instance_types,
                    existing_nodes=seeds, daemonsets=daemonsets,
                    unavailable=unavailable, allow_new_nodes=True,
                    relax=False, trace=trace,
                ).result()

            result, _outcome = relax_mod.refine(
                result, st, registry=self.registry,
                guard=self._guard if guarded else None, trace=trace,
                repair_solve=_repair,
            )
            return result
        # ktlint: allow[KT005] the rung is an optimization layer — any
        # routing failure ships the proven scan solution as a fallback
        except Exception:
            logger.warning("relax rung routing failed; scan solution ships",
                           exc_info=True)
            relax_mod.record_outcome(self.registry, "fallback")
            return result

    def _solve_wave(
        self, pods, provisioners, instance_types, existing_nodes, daemonsets,
        unavailable, allow_new_nodes, max_new_nodes, first=None,
        trace=None,
    ) -> SolveResult:
        """One pod wave with the preference-relaxation ladder applied.
        ``first`` short-circuits the all-preferences-hardened opening solve
        when the caller already dispatched it (submit's async first wave)."""
        result = first if first is not None else self._solve_once(
            [_harden_preferences(p) for p in pods], provisioners,
            instance_types, existing_nodes, daemonsets, unavailable,
            allow_new_nodes, max_new_nodes, trace=trace,
        )
        # cap the ladder depth like the reference caps its long axes
        # (SURVEY §5 long-context analog: 60-type truncation, batching):
        # a pod with absurdly many preferences drops straight to its last
        # MAX_RELAXATION_WAVES instead of funding one solve per preference
        max_pref = min(
            max((_n_preferences(p) for p in pods), default=0),
            MAX_RELAXATION_WAVES,
        )
        for keep in range(max_pref - 1, -1, -1):
            retry = [p for p in pods if p.name in result.infeasible
                     and _n_preferences(p) > keep]
            if not retry:
                continue
            _merge(result, self._solve_once(
                [_harden_preferences(p, keep) for p in retry],
                provisioners, instance_types,
                list(result.existing_nodes) + result.nodes, daemonsets,
                unavailable, allow_new_nodes,
                _budget_left(result, max_new_nodes), trace=trace,
            ))
        return result

    def _solve_once(
        self, pods, provisioners, instance_types, existing_nodes, daemonsets,
        unavailable, allow_new_nodes, max_new_nodes, dispatch=False,
        trace=None,
    ):
        # a hard capacity-type spread couples the whole batch to the
        # sequential engine (batch_needs_oracle) — exact interleaved
        # semantics, every backend
        if (self.backend == "oracle" or self._route_small(len(pods))
                or batch_needs_oracle(pods)):
            t0 = time.perf_counter()
            try:
                return oracle_solve(
                    pods, provisioners, instance_types,
                    existing_nodes=existing_nodes, daemonsets=daemonsets,
                    unavailable=unavailable, allow_new_nodes=allow_new_nodes,
                    max_new_nodes=max_new_nodes,
                )
            finally:
                self.registry.histogram(SOLVER_BACKEND_DURATION).observe(
                    time.perf_counter() - t0, {"backend": "oracle"}
                )
        if self._route_hier(pods, existing_nodes, allow_new_nodes,
                            max_new_nodes):
            from .hierarchy import solve_hierarchical

            result = solve_hierarchical(
                self, pods, provisioners, instance_types,
                daemonsets=daemonsets, unavailable=unavailable, trace=trace,
            )
            if result is not None:
                return result
            # None = flat is the right (or only warm) program for this
            # batch — the hier metrics label recorded why; fall through
        return self._solve_tpu(
            pods, provisioners, instance_types, existing_nodes, daemonsets,
            unavailable, allow_new_nodes, max_new_nodes, dispatch=dispatch,
            trace=trace,
        )

    #: startup-warmup shape profiles: (groups, total_pods, with_zone_spread).
    #: These mirror the steady-state controller batches — a provisioning wave
    #: of mixed pods, with and without topology spread (the selector-axis S
    #: rung differs between the two, so they are distinct compile
    #: signatures) — so the first real batches hit a compiled program; shapes
    #: outside the warmed ladder are covered by compile-behind
    #: (_device_ready), never by a caller stall.
    WARM_PROFILES = ((16, 400, False), (16, 400, True))

    #: megabatch slot rungs the startup precompile covers by default: the
    #: coalescer pads flushes to power-of-two rungs (tpu._mega_rung), so
    #: warming these serves every occupancy up to the default --max-slots
    WARM_MEGA_SLOTS = (2, 4, 8)

    def _profile_tensors(self, provisioners, instance_types, daemonsets,
                         profiles=None):
        """Tensorized startup-warmup batches, one per shape profile — the
        single source :meth:`warm_startup` (single-solve ladder) and
        :meth:`precompile_buckets` (megabatch rungs) both warm from."""
        from ..models.pod import TopologySpreadConstraint

        out = []
        for groups, total, spread in (profiles or self.WARM_PROFILES):
            pods = []
            per = max(1, total // groups)
            for gi in range(groups):
                sel = LabelSelector.of({"warmup-group": f"g{gi}"})
                constraints = (
                    [TopologySpreadConstraint(1, L.ZONE, "DoNotSchedule", sel)]
                    if spread else []
                )
                for i in range(per):
                    pods.append(PodSpec(
                        name=f"warmup-g{gi}-{i}",
                        labels={"warmup-group": f"g{gi}"},
                        requests={"cpu": 0.25 * (1 + gi % 8),
                                  "memory": float(2 ** (30 + gi % 3))},
                        topology_spread=list(constraints),
                        owner_key=f"warmup-g{gi}",
                    ))
            out.append(tensorize(pods, provisioners, instance_types,
                                 daemonsets=daemonsets))
        return out

    def warm_startup(
        self,
        provisioners,
        instance_types,
        daemonsets: Sequence[PodSpec] = (),
        existing_nodes: Sequence[SimNode] = (),
        profiles=None,
    ) -> int:
        """Kick off background compiles for the startup shape ladder against
        the live catalog/provisioners — and, crucially, against the live
        CLUSTER SIZE: ``existing_nodes`` (snapshots) set the NE/NR rungs, so
        an operator restarting over a 500-node cluster warms the shapes its
        provisioning and consolidation solves will actually hit, not the
        empty-cluster ones.  Returns the number of compiles accepted.  Cheap
        to call repeatedly (signatures dedupe), so the operator re-invokes
        it on settings changes that reshape the catalog."""
        from . import relax as relax_mod

        if (self.backend not in ("auto", "tpu") or not self.compile_behind
                or not self._guard.healthy):
            return 0
        started = 0
        for st in self._profile_tensors(provisioners, instance_types,
                                        daemonsets, profiles):
            # provisioning shape: batch solved against the current cluster
            if self._tpu.warm_async(st, existing_nodes=existing_nodes,
                                    mesh=self.mesh, on_done=self._warm_done):
                started += 1
            # the relax rung's program for the same shape (KT_RELAX): the
            # first refinable solve then runs the rung instead of
            # skip-and-warm-behind (KT014 audits this grid's coverage)
            if relax_mod.relax_enabled() and relax_mod.warm_relax(
                    self._tpu, st):
                started += 1
            if existing_nodes:
                # consolidation what-if shape: a small repack against the
                # cluster with at most one new node (deprovisioning.py
                # _solve_what_if passes max_new_nodes=1)
                if self._tpu.warm_async(
                    st, existing_nodes=existing_nodes,
                    max_nodes=len(existing_nodes) + 1,
                    mesh=self.mesh, on_done=self._warm_done,
                ):
                    started += 1
        if started:
            self.registry.gauge(SOLVER_COMPILE_IN_PROGRESS).set(
                self._tpu.compiles_in_flight()
            )
            logger.info("startup warmup: %d solver shape compiles accepted "
                        "in the background", started)
        return started

    def precompile_buckets(
        self,
        provisioners,
        instance_types,
        daemonsets: Sequence[PodSpec] = (),
        existing_nodes: Sequence[SimNode] = (),
        profiles=None,
        mega_slots: Optional[Sequence[int]] = None,
        wait: bool = False,
        timeout: float = 1800.0,
    ) -> int:
        """Ahead-of-time bucket-grid precompile: the startup single-solve
        ladder (:meth:`warm_startup`) PLUS the megabatch programs at the
        given request-slot rungs, so both the serial and the coalesced
        serving paths are warm before the first RPC.  ``wait=True`` blocks
        until every accepted compile lands (the ``serve --warmup`` path) and
        observes the total in ``karpenter_solver_precompile_duration_seconds``
        — pair with ``--jit-cache-dir`` and restarts skip even this.
        Returns the number of compiles accepted."""
        t0 = time.perf_counter()
        started = self.warm_startup(
            provisioners, instance_types, daemonsets=daemonsets,
            existing_nodes=existing_nodes, profiles=profiles,
        )
        if (self.backend in ("auto", "tpu") and self.compile_behind
                and self._guard.healthy and mesh_shardable(self.mesh)):
            # meshed schedulers warm the SHARDED rung ladder: warm_async
            # resolves each requested slot count to its sharded rung (floor
            # = device count), and signature dedupe collapses requests that
            # land on the same rung — the default (2, 4, 8) grid on an
            # 8-device mesh warms exactly the 8-slot sharded program
            rungs = sorted({
                s for s in (mega_slots or self.WARM_MEGA_SLOTS)
                if 2 <= s <= MEGA_MAX_SLOTS
            })
            for st in self._profile_tensors(provisioners, instance_types,
                                            daemonsets, profiles):
                for s in rungs:
                    if self._tpu.warm_async(
                        st, existing_nodes=existing_nodes, slots=s,
                        mesh=self.mesh, on_done=self._warm_done,
                    ):
                        started += 1
        if wait and started:
            deadline = time.perf_counter() + timeout
            while (not self._tpu.warm_idle()
                   and time.perf_counter() < deadline):
                time.sleep(0.25)
            self.registry.histogram(PRECOMPILE_DURATION).observe(
                time.perf_counter() - t0)
            if not self._tpu.warm_idle():
                logger.warning("bucket precompile still running after %.0fs "
                               "wait budget; remaining compiles finish "
                               "behind", timeout)
            else:
                logger.info("bucket precompile complete: %d programs in "
                            "%.1fs", started, time.perf_counter() - t0)
        return started

    # ---- compile-behind (cold-start) ----------------------------------
    def stop_warms(self) -> None:
        """Stop background compiles (operator shutdown): queued warms are
        dropped; exit waits only for compiles already in flight.  Also stops
        the device-guard recovery probe."""
        self._tpu.stop_warms()
        self._guard.stop()

    def _warm_done(self, sig, seconds: float, err) -> None:
        # this callback runs BEFORE the warm thread clears its own in-flight
        # entry (TpuSolver keeps it until after on_done so watchers that
        # poll compiles_in_flight() down to 0 never miss these metrics);
        # exclude the completing compile from the gauge
        self.registry.gauge(SOLVER_COMPILE_IN_PROGRESS).set(
            max(0, self._tpu.compiles_in_flight() - 1)
        )
        if err is not None:
            # failed compiles stay out of the duration histogram — it
            # documents actual compile cost; TpuSolver arms a per-shape
            # retry backoff so this shape isn't hot-recompiled
            logger.warning("background solver compile failed after %.1fs: %r",
                           seconds, err)
        else:
            self.registry.histogram(SOLVER_COMPILE_DURATION).observe(seconds)
            logger.info("solver shape compiled in background (%.1fs); "
                        "subsequent solves of this shape run on-device", seconds)

    def _device_ready(self, st, existing_nodes, max_slots) -> bool:
        """True when the device program for this solve's shape is already
        compiled.  (The background compile for a cold shape is kicked off by
        _start_warm AFTER the fallback solve returns, so the compile thread
        never contends with the caller's own solve.)"""
        sig = self._tpu.signature(
            st, existing_nodes=existing_nodes, max_nodes=max_slots,
            mesh=self.mesh,
        )
        return self._tpu.ready(sig)

    def _start_warm(self, st, existing_nodes, max_slots) -> None:
        """Kick the background compile for a shape that just went cold,
        with snapshot inputs so the live node objects aren't shared with
        the worker thread.  Logged once per shape."""
        if not self.compile_behind or not self._guard.healthy:
            return  # a compile against a wedged device would hang its thread
        started = self._tpu.warm_async(
            st, existing_nodes=[n.snapshot() for n in existing_nodes],
            max_nodes=max_slots, mesh=self.mesh, on_done=self._warm_done,
        )
        if started:
            self.registry.gauge(SOLVER_COMPILE_IN_PROGRESS).set(
                self._tpu.compiles_in_flight()
            )
        sig = self._tpu.signature(
            st, existing_nodes=existing_nodes, max_nodes=max_slots,
            mesh=self.mesh,
        )
        with self._cold_lock:
            first_time = sig not in self._cold_logged
            self._cold_logged.add(sig)
        if first_time:
            logger.info(
                "device program for this solve shape was not compiled yet; "
                "served from the warm tier (compile running in background: "
                "%s)", started or self._tpu.compiling(sig),
            )

    def _cold_solve(
        self, st, tpu_pods, provisioners, instance_types, all_existing,
        daemonsets, unavailable, allow_new_nodes, max_slots, max_new_nodes,
    ):
        """Serve a solve whose device program is still compiling: the native
        C++ tier when it can express the batch (ms-scale, zero warmup — the
        Go-FFD-like cold-start answer), else the CPU oracle."""
        from . import native as native_mod

        if native_mod.available() and not native_mod.has_topology(st):
            res = native_mod.solve_tensors_native(
                st, existing_nodes=all_existing, max_nodes=max_slots,
            )
            return res, "native"
        res = oracle_solve(
            tpu_pods, provisioners, instance_types,
            existing_nodes=all_existing, daemonsets=daemonsets,
            unavailable=unavailable, allow_new_nodes=allow_new_nodes,
            max_new_nodes=max_new_nodes,
        )
        return res, "oracle"

    def _route_small(self, n_pods: int) -> bool:
        """auto-policy: STEADY-STATE batches below the device-dispatch
        crossover are served by the sequential CPU oracle — exact-parity FFD
        at ~ms latency for any constraint shape (r4 weak #3: the native
        tier's small-shape answer was 19-20 nodes where oracle/device pack
        16, and it was serving those batches permanently).  The native tier
        still serves COLD shapes of any size while the device program
        compiles behind (_cold_solve) — that is where its 50k-in-224ms
        speed, not its packing polish, is the right trade."""
        return self.backend == "auto" and n_pods <= self.native_batch_limit

    def _route_hier(self, pods, existing_nodes, allow_new_nodes,
                    max_new_nodes) -> bool:
        """Hierarchical routing gate: flat below ``KT_HIER_THRESHOLD`` pods
        (default 100k), block decomposition at/above it — greenfield
        batches only (no existing nodes, unbounded budget: the delta chain
        and retry waves keep flat's exact placed-snapshot semantics), on a
        healthy device tier, with no device-inexpressible pods (the flat
        path owns that oracle carve-out)."""
        from .hierarchy import hier_threshold

        thr = hier_threshold()
        return (
            thr > 0
            and not getattr(self, "_hier_depth", 0)
            and self.backend in ("auto", "tpu")
            and len(pods) >= thr
            and not existing_nodes
            and allow_new_nodes
            and max_new_nodes is None
            and self._guard.healthy
            and not any(device_inexpressible(p) for p in pods)
        )

    def _route_native(self, st, n_pods: int) -> bool:
        """Forced native backend only.  The auto policy no longer serves
        steady-state batches from the native tier: small batches go to the
        oracle (_route_small, exact parity), large ones to the device; the
        native tier serves cold shapes via _cold_solve."""
        return self.backend == "native"

    def _tensorize(self, pods, provisioners, instance_types, daemonsets,
                   unavailable, trace=NULL_TRACE) -> Tuple["object", float]:
        """Host tensorize through the incremental cache (steady-state: a
        lookup plus a counts vector — models/tensorize.TensorizeCache).
        Returns (tensors, seconds spent)."""
        t0 = time.perf_counter()
        with trace.span("tensorize") as span:
            if self._tensorize_cache is not None:
                st, tier = self._tensorize_cache.tensorize(
                    pods, provisioners, instance_types,
                    daemonsets=daemonsets, unavailable=unavailable,
                )
            else:
                st = tensorize(
                    pods, provisioners, instance_types,
                    daemonsets=daemonsets, unavailable=unavailable,
                )
                tier = "off"
            span.annotate(tier=tier)
        dt = time.perf_counter() - t0
        self.registry.histogram(TENSORIZE_DURATION).observe(dt)
        if tier in ("identity", "shape"):
            self.registry.counter(TENSORIZE_CACHE_HITS).inc({"tier": tier})
        elif tier == "miss":
            self.registry.counter(TENSORIZE_CACHE_MISSES).inc()
        return st, dt

    def _flight_anomaly(self, reason: str, detail: str, trace) -> None:
        """Hand an anomaly (hang-guard trip, degraded solve) to the flight
        recorder with the in-flight trace, so the dump explains THIS solve,
        not just the ring before it.  Best-effort by contract: this sits on
        the degraded/hang FALLBACK paths, where a failure to record must
        never fail the solve the warm tier is about to serve."""
        try:
            flight = getattr(self.tracer, "flight", None)
            if flight is not None:
                flight.anomaly(reason, detail=detail,
                               trace=trace if trace else None)
        except Exception:  # noqa: BLE001 — observability must not fail solves
            logger.warning("flight-recorder anomaly dump failed (%s)",
                           reason, exc_info=True)

    def _solve_tpu(
        self, pods, provisioners, instance_types, existing_nodes, daemonsets,
        unavailable, allow_new_nodes, max_new_nodes, dispatch=False,
        trace=None,
    ):
        """Device-tier wave.  Returns a SolveResult — or, when ``dispatch``
        is set and the batch takes the plain already-compiled device path
        with no oracle carve-outs, a :class:`_PendingWave` whose ``finish``
        fences the async dispatch (the pipelined-overlap window lives
        between the two)."""
        trace = trace or NULL_TRACE
        # carve out pods the device solver can't express (rare shapes only)
        tpu_pods = [p for p in pods if not device_inexpressible(p)]
        cpu_pods = [p for p in pods if device_inexpressible(p)]

        # positive affinity couples the two batches: whichever side's
        # affinity selectors match the other side's pods must solve SECOND,
        # so the counts it co-locates against already exist.  Default (and
        # tie-break) is device-first, oracle against its result.
        def _refers(src, dst):
            sels = [t.label_selector for p in src for t in p.affinity_terms
                    if not t.anti]
            return any(s.matches(q.labels) for s in sels for q in dst)

        cpu_first = bool(cpu_pods and tpu_pods
                         and _refers(tpu_pods, cpu_pods)
                         and not _refers(cpu_pods, tpu_pods))

        # placed-snapshot chaining: each stage solves against the previous
        # stage's PLACED existing snapshots (+ placed prior new nodes), and
        # the placed copies replace the prior references afterwards — see
        # _merge for the cross-wave bookkeeping rationale
        cur_existing: List[SimNode] = list(existing_nodes)
        nodes: List[SimNode] = []
        assignments: Dict[str, str] = {}
        infeasible: Dict[str, str] = {}
        solve_ms = 0.0
        tensorize_ms = 0.0
        served_cold = False

        def chain(res: SolveResult) -> None:
            """Adopt a stage's placed snapshots of (cur_existing + nodes)."""
            nonlocal cur_existing, nodes
            cur_existing, nodes = _adopt_placed(cur_existing, res)

        if cpu_first:
            res0 = oracle_solve(
                cpu_pods, provisioners, instance_types,
                existing_nodes=cur_existing, daemonsets=daemonsets,
                unavailable=unavailable, allow_new_nodes=allow_new_nodes,
                max_new_nodes=max_new_nodes,
            )
            chain(res0)
            assignments.update(res0.assignments)
            infeasible.update(res0.infeasible)
            solve_ms += res0.solve_ms
            cpu_pods = []
            if max_new_nodes is not None:
                max_new_nodes = max(0, max_new_nodes - len(res0.nodes))

        def _tail() -> SolveResult:
            """cpu-carve-out epilogue + result assembly — shared verbatim by
            the synchronous return and the async wave's finish."""
            nonlocal cur_existing, nodes, solve_ms
            if cpu_pods:
                t0c = time.perf_counter()
                res2 = oracle_solve(
                    cpu_pods, provisioners, instance_types,
                    existing_nodes=list(cur_existing) + nodes,
                    daemonsets=daemonsets, unavailable=unavailable,
                    allow_new_nodes=allow_new_nodes,
                    max_new_nodes=None if max_new_nodes is None else max(0, max_new_nodes - len(nodes)),
                )
                self.registry.histogram(SOLVER_BACKEND_DURATION).observe(
                    time.perf_counter() - t0c, {"backend": "oracle"}
                )
                chain(res2)
                assignments.update(res2.assignments)
                infeasible.update(res2.infeasible)
                solve_ms += res2.solve_ms
            return SolveResult(
                nodes=nodes,
                assignments=assignments,
                infeasible=infeasible,
                existing_nodes=cur_existing,
                solve_ms=solve_ms,
                tensorize_ms=tensorize_ms,
                served_cold=served_cold,
            )

        if not tpu_pods:
            return _tail()

        st, tsec = self._tensorize(
            tpu_pods, provisioners, instance_types, daemonsets, unavailable,
            trace=trace)
        tensorize_ms += tsec * 1000.0
        t0 = time.perf_counter()
        new_budget = len(tpu_pods) if max_new_nodes is None else max_new_nodes
        all_existing = list(cur_existing) + nodes
        max_slots = len(all_existing) + new_budget

        def _adopt_device(res: SolveResult, backend_used: str) -> SolveResult:
            """Post-device bookkeeping (metrics, what-if filtering, chain) —
            identical for the sync and async returns."""
            nonlocal solve_ms
            trace.annotate(backend_used=backend_used)
            self.registry.histogram(SOLVER_BACKEND_DURATION).observe(
                time.perf_counter() - t0, {"backend": backend_used}
            )
            if not allow_new_nodes and res.nodes:
                # consolidation what-if with no new nodes allowed: pods that
                # needed new nodes are infeasible
                for n in res.nodes:
                    for p in n.pods:
                        infeasible[p.name] = "needs a new node (disallowed)"
                res.nodes = []
                for p in list(res.assignments):
                    if p in infeasible:
                        del res.assignments[p]
            chain(res)
            assignments.update(res.assignments)
            infeasible.update(res.infeasible)
            solve_ms += res.solve_ms
            return _tail()

        def _cold_fallback() -> Tuple[SolveResult, str]:
            """Warm-tier serve for a still-compiling shape (transient: the
            reseat epilogue skips it so the cold path keeps its latency
            contract; the device program takes over once compiled)."""
            nonlocal served_cold
            res, backend_used = self._cold_solve(
                st, tpu_pods, provisioners, instance_types, all_existing,
                daemonsets, unavailable, allow_new_nodes, max_slots,
                max_new_nodes,
            )
            served_cold = True
            trace.annotate(served_cold=True)
            self.registry.counter(SOLVER_COLD_FALLBACKS).inc(
                {"backend": backend_used}
            )
            self._start_warm(st, all_existing, max_slots)
            return res, backend_used

        def _degraded_fallback() -> Tuple[SolveResult, str]:
            """Warm-tier serve while the device tier is latched unhealthy.
            NOT a cold-start fallback (the program was compiled, the device
            was not answering — distinct counter so outage traffic can't
            pollute cold-start SLOs) and NOT flagged served_cold: degraded
            answers provision real long-lived nodes (nothing supersedes
            them when a compile lands), so they keep the reseat polish.
            No _start_warm either: a background compile against a wedged
            device would hang its warm thread too."""
            res, backend_used = self._cold_solve(
                st, tpu_pods, provisioners, instance_types, all_existing,
                daemonsets, unavailable, allow_new_nodes, max_slots,
                max_new_nodes,
            )
            self.registry.counter(SOLVER_DEGRADED_SOLVES).inc(
                {"backend": backend_used}
            )
            trace.annotate(degraded=True)
            self._flight_anomaly(
                "degraded_solve",
                f"device tier latched unhealthy; {len(tpu_pods)}-pod batch "
                f"served by the warm {backend_used} tier", trace)
            return res, backend_used

        if self._route_native(st, len(tpu_pods)):
            from . import native as native_mod

            res = native_mod.solve_tensors_native(
                st, existing_nodes=all_existing, max_nodes=max_slots,
            )
            return _adopt_device(res, "native")
        if self.backend == "auto" and not self._device_ready(
            st, all_existing, max_slots
        ):
            # compile-behind: the device program for this shape is not
            # compiled yet; serve this solve from the warm tier so the
            # caller never eats the XLA stall, then _start_warm (inside
            # _cold_fallback, after the fallback returns) kicks the
            # background compile
            res, backend_used = _cold_fallback()
            return _adopt_device(res, backend_used)

        guarded = self.backend == "auto" and self._guard.enabled
        degraded = guarded and not self._guard.healthy
        raise_on_exhaust = self.backend == "auto" and self.compile_behind

        collector = self._mega_collect
        if dispatch and not degraded and collector is not None:
            # megabatch registration (submit_many): the first device wave
            # joins the collector's pending batch instead of dispatching;
            # ONE vmapped device call later serves every slot (SHARDED over
            # the mesh's chips for a meshed scheduler).  The fallback
            # ladder at fence time is identical to the single async path —
            # per REQUEST, so one exhausted/hung slot degrades itself only.
            slot = collector.add(
                st=st, existing_nodes=all_existing, max_nodes=max_slots,
                raise_on_exhaust=raise_on_exhaust, trace=trace,
            )

            def _finish_mega() -> SolveResult:
                try:
                    out = slot.result()
                    return _adopt_device(out.result, "tpu")
                except SlotsExhausted:
                    res, backend_used = _cold_fallback()
                    return _adopt_device(res, backend_used)
                except DeviceHang:
                    self._flight_anomaly(
                        "device_hang", "megabatch device dispatch hung past "
                        "the guard deadline (wedged tunnel?)", trace)
                    res, backend_used = _degraded_fallback()
                    return _adopt_device(res, backend_used)

            return _PendingWave(_finish_mega)

        if dispatch and not degraded:
            # async dispatch: enqueue the device program WITHOUT fencing and
            # hand the fence back as a _PendingWave — the caller (submit /
            # SolvePipeline) tensorizes batch N+1 in the window between
            # dispatch and finish while this batch executes on the device.
            # The fallback ladder (slots-exhausted → warm tier, hang →
            # degraded tier) runs at fence time, identical to the sync path;
            # the dispatch itself is guarded too (H2D transfers through a
            # wedged tunnel can hang exactly like the fence).
            def _dispatch_call():
                return self._tpu.solve_async(
                    st, existing_nodes=all_existing, max_nodes=max_slots,
                    mesh=self.mesh, raise_on_exhaust=raise_on_exhaust,
                    trace=trace,
                )

            try:
                pending = (self._guard.run(_dispatch_call) if guarded
                           else _dispatch_call())
            except DeviceHang:
                self._flight_anomaly(
                    "device_hang", "H2D dispatch hung past the guard "
                    "deadline (wedged tunnel?)", trace)
                res, backend_used = _degraded_fallback()
                return _adopt_device(res, backend_used)

            def _finish_wave() -> SolveResult:
                try:
                    out = (self._guard.run(pending.result) if guarded
                           else pending.result())
                    return _adopt_device(out.result, "tpu")
                except SlotsExhausted:
                    res, backend_used = _cold_fallback()
                    return _adopt_device(res, backend_used)
                except DeviceHang:
                    self._flight_anomaly(
                        "device_hang", "device fence hung past the guard "
                        "deadline (wedged tunnel?)", trace)
                    res, backend_used = _degraded_fallback()
                    return _adopt_device(res, backend_used)

            return _PendingWave(_finish_wave)

        def _device_call():
            return self._tpu.solve(
                st, existing_nodes=all_existing, max_nodes=max_slots,
                mesh=self.mesh, raise_on_exhaust=raise_on_exhaust,
                trace=trace,
            )

        if not degraded:
            try:
                out = (self._guard.run(_device_call) if guarded
                       else _device_call())
                return _adopt_device(out.result, "tpu")
            except SlotsExhausted:
                # the optimistic node-slot axis ran out and the full-budget
                # program is cold: serve from the warm tier now, compile the
                # full program behind (the solver remembered the exhaustion,
                # so _start_warm targets it)
                res, backend_used = _cold_fallback()
                return _adopt_device(res, backend_used)
            except DeviceHang:
                # the guard latched the device tier unhealthy; serve THIS
                # batch from the warm tier like every batch until the
                # recovery probe succeeds
                self._flight_anomaly(
                    "device_hang", "device solve hung past the guard "
                    "deadline (wedged tunnel?)", trace)
        res, backend_used = _degraded_fallback()
        return _adopt_device(res, backend_used)
