"""Convex-relaxation refinement rung — better-than-FFD node cost, on device.

The vectorized scan (solver/tpu.py) IS sequential first-fit-decreasing, so
its node cost is locked at ~0.99x the FFD oracle no matter how fast it
runs (ROADMAP item 4).  The gap to a globally-optimized packing is
structural: the scan commits each pod GROUP to the locally-cheapest
$/pod candidate at that group's step, so it can never discover that a
cpu-heavy group and a memory-heavy group sharing one balanced node type is
cheaper than each buying its own density-optimal fleet — the
backfill-aware scoring estimates later demand, it never re-decides an
earlier group's type.  CvxCluster (PAPERS.md) solves exactly this class of
large granular allocation problems via per-agent decomposable convex
relaxations, the shape that jits and vmaps; "Priority Matters" (PAPERS.md)
shows constraint-based packing beating greedy heuristics on real clusters.

This module is that rung, built to the repo's serving discipline:

- **The relaxation is a fixed-iteration, fixed-shape device program.**
  Variables ``x[g, c]`` — fractional pods of group ``g`` on candidate
  ``c`` — minimize the fractional node-cost objective
  ``sum_c price_c * max_r(load_cr / alloc_cr)`` (the LP node count of a
  candidate is its bottleneck-resource utilization) by entropic mirror
  descent on the per-group scaled simplexes: multiplicative weights with a
  row-normalized subgradient, the ``max_r`` smoothed by a sharp softmax,
  best-true-cost iterate tracked through the ``lax.scan``.  Shapes pad to
  the SAME ``solve_dims`` G/C rungs the scan compiles at (``relax_dims``
  delegates — never invents a key), and the iteration count buckets onto
  ``RELAX_ITER_RUNGS``, so the program precompiles onto a bounded ladder
  exactly like every other XLA program here (KT008/KT014).  Chosen over a
  host-side LP solver deliberately: scipy's simplex would be exact but is
  a serial host dependency with data-dependent runtime; the mirror-descent
  rung is ~1 ms of dense [G, C] arithmetic with a hard iteration bound,
  and the min-cost select below makes exactness unnecessary for
  correctness — only for win-rate.
- **Rounding reaches integrality on the host, repair seeds the scan.**
  Largest-remainder integerization per group, then a per-candidate
  first-fit (groups descending by the solvers' shared FFD magnitude) into
  whole nodes of the chosen type, provisioner limits and the pods-resource
  row enforced from the same tensors the scan packs with.  Pods the
  rounding strands (integrality slack, a limit binding) first-fit into the
  open capacity of the rounded fleet — the vectorized prefix-allocation
  pattern of the PR-6 warm-start host tier — and any remainder re-solves
  through the caller's ``repair_solve`` hook: the existing scan, SEEDED
  from the rounded solution as its existing-node state (the PR-6
  machinery), so repair composes spread/affinity-exactly with everything
  already placed.
- **Never worse by construction.**  Only *unconstrained* pod groups are
  eligible (no spread/affinity/hostname caps, no zone/capacity-type
  pinning, nothing watching them through a constraint selector, fully
  placed on solver-proposed nodes whose every pod is itself eligible) —
  constraint-bearing pods keep their scan seats as fixed boundary
  conditions.  The rung re-packs the eligible pods, self-validates the
  rounded fleet (capacity, exactly-once assignment), and the solver ships
  whichever of {scan, relax+round} costs strictly less:
  ``karpenter_solver_relax_total{outcome=improved|tied|fallback|skipped}``
  partitions every evaluation.

Knobs: ``KT_RELAX`` (default on) gates the rung, ``KT_RELAX_ITERS``
(default 64, bucketed up to RELAX_ITER_RUNGS) sets the descent budget,
``KT_RELAX_DELTA`` (default off) opts delta-chain full-solve boundaries in
(solver/scheduler.py routes; delta scan steps and megabatch slots always
skip — the rung buys $ at latency, the wrong trade on those paths).
"""

from __future__ import annotations

import logging
import os
import time
from functools import partial
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics import (
    RELAX_DURATION,
    RELAX_IMPROVEMENT,
    RELAX_OUTCOMES,
    RELAX_TOTAL,
    Registry,
    registry as default_registry,
)
from ..gang import gang_fixed
from ..models import labels as L
from ..obs.trace import NULL_TRACE
from .types import SimNode, SolveResult

logger = logging.getLogger(__name__)

#: iteration-count compile rungs: KT_RELAX_ITERS buckets UP onto this
#: ladder (smallest rung >= the ask; the top rung caps it), so the relax
#: program's compile signatures stay log-bounded and precompilable exactly
#: like the tensor-axis rungs (KT014 audits the ladder's health)
RELAX_ITER_RUNGS = (32, 64, 128, 256)
DEFAULT_RELAX_ITERS = 64

#: softmax sharpness smoothing the per-candidate max_r bottleneck (the
#: objective's only non-smooth piece); the best-TRUE-cost iterate tracking
#: makes the smoothing a descent aid, never a correctness input
_TAU = 64.0
#: mirror-descent step on the range-normalized subgradient
_ETA = 1.0


def mirror_eta(t):
    """Step size η/√(1+t/8) of the mirror-descent ladder at iteration ``t``
    (float — pass ``t.astype(jnp.float32)`` from traced code).  One source
    for the schedule: the relax rung's multiplicative-weights loop and the
    hierarchical price ascent (solver/hierarchy.py) share it so the two
    rungs decay in lockstep."""
    return _ETA / jnp.sqrt(1.0 + t / 8.0)


def relax_enabled() -> bool:
    return os.environ.get("KT_RELAX", "1") != "0"


def relax_delta_enabled() -> bool:
    """Whether delta-chain FULL-solve boundaries run the rung (default
    off: a delta chain is the latency path; KT_RELAX_DELTA=1 opts in)."""
    return os.environ.get("KT_RELAX_DELTA", "0") == "1"


def configured_iters() -> int:
    """The live iteration budget, read through the knob registry
    (ISSUE 19): a tuned override wins, else the registry falls back to
    ``KT_RELAX_ITERS``/the default at call time — env workflows are
    untouched until the controller actually moves the knob."""
    from ..tuning.knobs import global_knobs

    try:
        # ktlint: allow[KT014] registry knob NAME, not a key tail
        return int(global_knobs().get("relax_iters"))
    except (TypeError, ValueError):
        return DEFAULT_RELAX_ITERS


def iter_rung(n: int) -> int:
    """Bucket an iteration ask UP onto RELAX_ITER_RUNGS (top rung caps)."""
    for r in RELAX_ITER_RUNGS:
        if n <= r:
            return r
    return RELAX_ITER_RUNGS[-1]


def _relax_key_tail(relax_iters: int) -> tuple:
    """The relax program's compile-key suffix — the SINGLE source of this
    format; ``relax_signature`` and the KT014 audit both anchor on it."""
    return (("relax_iters", relax_iters),)


def relax_dims(st) -> dict:
    """The relax program's padded dims: the G/C rungs of the scan's own
    ``solve_dims`` bucketing (delegated — the single source of the
    bucketing math; an invented key would be a compile axis no rung ladder
    bounds, KT014) plus the resource width."""
    from .tpu import solve_dims

    # NE/node_budget only shape the NR axis, which the relax program does
    # not carry; the minimal budget keeps the delegate's estimate cheap
    dims = solve_dims(st, NE=0, node_budget=1)
    return dict(G=dims["G"], C=dims["C"], R=dims["R"])


def relax_signature(st, relax_iters: Optional[int] = None) -> tuple:
    """Compile signature of the relax program for this tensor shape — the
    key TpuSolver readiness/warm bookkeeping tracks for it."""
    from .tpu import _dims_key

    iters = iter_rung(configured_iters() if relax_iters is None
                      else relax_iters)
    return (("relax", True),) + _dims_key(relax_dims(st)) \
        + _relax_key_tail(iters)


def zero_init_metrics(registry: Registry) -> None:
    """Register the relax series at 0 so rate()/increase() never lose the
    first evaluation (KT003)."""
    for outcome in RELAX_OUTCOMES:
        if not registry.counter(RELAX_TOTAL).has({"outcome": outcome}):
            registry.counter(RELAX_TOTAL).inc({"outcome": outcome},
                                              value=0.0)
    registry.histogram(RELAX_DURATION)
    if not registry.gauge(RELAX_IMPROVEMENT).has():
        # 1.0 = parity (no comparison yet): the series exists from
        # construction without claiming an improvement that never ran
        registry.gauge(RELAX_IMPROVEMENT).set(1.0)


def record_outcome(registry: Registry, outcome: str,
                   seconds: Optional[float] = None,
                   ratio: Optional[float] = None) -> None:
    registry.counter(RELAX_TOTAL).inc({"outcome": outcome})
    if seconds is not None:
        registry.histogram(RELAX_DURATION).observe(seconds)
    if ratio is not None:
        registry.gauge(RELAX_IMPROVEMENT).set(ratio)


# ---------------------------------------------------------------------------
# the device program
# ---------------------------------------------------------------------------


def _relax_program(req, counts, feas, alloc_inv, price, x0,
                   relax_iters: int):
    """Entropic mirror descent on the fractional allocation relaxation.

    ``req[G, R]`` per-pod requests, ``counts[G]`` pods per group (0 for
    ineligible/padding rows), ``feas[G, C]`` bool feasibility,
    ``alloc_inv[C, R]`` reciprocal candidate allocatable (0 where the
    candidate lacks the resource), ``price[C]`` effective $/hr (cheapest
    available offering), ``x0[G, C]`` warm start (the scan's own
    solution).  Objective ``f(x) = sum_c price_c * max_r(load_cr *
    alloc_inv_cr)`` — convex (max of linears); minimized over the product
    of per-group scaled simplexes by multiplicative-weights updates.
    Returns ``(best_x, best_cost)`` — the best TRUE-objective iterate, so
    the softmax smoothing inside the gradient can never degrade the
    reported solution below the warm start."""
    feas_f = feas.astype(jnp.float32)

    def renorm(x):
        x = x * feas_f
        s = jnp.sum(x, axis=1, keepdims=True)
        return jnp.where(s > 1e-30, x / jnp.maximum(s, 1e-30), 0.0) \
            * counts[:, None]

    def util(x):
        return (x.T @ req) * alloc_inv            # [C, R]

    def cost(x):
        return jnp.sum(price * jnp.max(util(x), axis=1))

    def grad(x):
        w = jax.nn.softmax(_TAU * util(x), axis=1)  # [C, R] bottleneck mix
        return req @ (price[:, None] * w * alloc_inv).T  # [G, C]

    x_init = renorm(x0)

    def step(carry, t):
        x, bx, bf = carry
        g = grad(x)
        gmin = jnp.min(jnp.where(feas, g, jnp.inf), axis=1, keepdims=True)
        gmax = jnp.max(jnp.where(feas, g, -jnp.inf), axis=1, keepdims=True)
        spread = jnp.maximum(gmax - gmin, 1e-12)
        eta = mirror_eta(t.astype(jnp.float32))
        x = renorm(x * jnp.exp(-eta * (g - gmin) / spread))
        f = cost(x)
        better = f < bf
        bx = jnp.where(better, x, bx)
        bf = jnp.where(better, f, bf)
        return (x, bx, bf), jnp.int32(0)

    (x, bx, bf), _ = jax.lax.scan(
        step, (x_init, x_init, cost(x_init)),
        jnp.arange(relax_iters, dtype=jnp.int32))
    return bx, bf


#: module-level jitted program (KT008: the wrapper is created once; the
#: iteration rung is the only static axis beyond the padded shapes)
relax_jit = partial(jax.jit, static_argnames=("relax_iters",))(
    _relax_program
)


# ktlint: fence the relax rung's one D2H read — the refinement program's
# result comes back here, strictly after the main solve already fenced
def _run_relax(req, counts, feas, alloc_inv, price, x0, relax_iters: int,
               guard=None) -> Tuple[np.ndarray, float]:
    def call():
        return relax_jit(req, counts, feas, alloc_inv, price, x0,
                         relax_iters=relax_iters)

    bx, bf = guard.run(call) if guard is not None else call()
    return np.asarray(bx), float(np.asarray(bf))


# ktlint: fence the warm thunk's D2H read is the deliberate compile+fence
# of the background relax-program warm (discarded results, warm thread)
def warm_relax(solver, st, relax_iters: Optional[int] = None) -> bool:
    """Background-compile the relax program for this tensor shape on the
    solver's warm machinery (concurrency cap, bounded queue, failure
    backoff) — the compile-behind contract: the serving path skips the
    rung while its program is cold and never stalls on XLA."""
    iters = iter_rung(configured_iters() if relax_iters is None
                      else relax_iters)
    sig = relax_signature(st, iters)
    dims = relax_dims(st)
    Gp, Cp, R = dims["G"], dims["C"], dims["R"]

    def thunk():
        req = np.zeros((Gp, R), dtype=np.float32)
        req[:, :1] = 1.0
        counts = np.ones(Gp, dtype=np.float32)
        feas = np.ones((Gp, Cp), dtype=bool)
        alloc_inv = np.ones((Cp, R), dtype=np.float32)
        price = np.ones(Cp, dtype=np.float32)
        x0 = np.ones((Gp, Cp), dtype=np.float32)
        bx, _bf = relax_jit(req, counts, feas, alloc_inv, price, x0,
                            relax_iters=iters)
        np.asarray(bx)  # fence: the compile has landed
        solver._mark_ready(sig)

    return solver.warm_custom(sig, thunk)


# ---------------------------------------------------------------------------
# host-side eligibility + feasibility
# ---------------------------------------------------------------------------


def _host_feasibility(st) -> np.ndarray:
    """Numpy mirror of the device feasibility (labels & fit & provisioner)
    — byte-identical semantics to ops/feasibility's gather path, cheap at
    group granularity ([G, C, K] bit gathers)."""
    G, C = st.G, st.C
    if G == 0 or C == 0:
        return np.zeros((G, C), dtype=bool)
    K = st.pm.shape[1]
    vw = np.asarray(st.cand_vw)                      # [C, K]
    vb = np.asarray(st.cand_vb).astype(np.uint32)
    g_idx = np.arange(G)[:, None, None]              # [G, 1, 1]
    k_idx = np.arange(K)[None, None, :]              # [1, 1, K]
    words = np.asarray(st.pm)[g_idx, k_idx, vw[None, :, :]]  # [G, C, K]
    bits = ((words >> vb[None, :, :]) & np.uint32(1)).astype(bool)
    lab = np.all(bits | ~np.asarray(st.key_check)[None, None, :], axis=2)
    req = np.asarray(st.requests, dtype=np.float32)  # [G, R]
    alloc = np.asarray(st.cand_alloc, dtype=np.float32)
    fit = np.all((req[:, None, :] <= alloc[None, :, :] + 1e-6)
                 | (req[:, None, :] <= 0), axis=2)
    gp = np.asarray(st.gp_ok)[np.arange(G)[:, None],
                              np.asarray(st.cand_prov)[None, :]]
    return lab & fit & gp


def _host_dom_ok(st) -> np.ndarray:
    """Numpy mirror of the device per-group domain allowance [G, D]."""
    zone_key = st.vocab.key_id[L.ZONE]
    ct_key = st.vocab.key_id[L.CAPACITY_TYPE]
    pm = np.asarray(st.pm)
    dom_vw = np.asarray(st.dom_vw)
    dom_vb = np.asarray(st.dom_vb).astype(np.uint32)
    zw = pm[:, zone_key, :][:, dom_vw[:, 0]]         # [G, D]
    zok = ((zw >> dom_vb[None, :, 0]) & np.uint32(1)).astype(bool)
    cw = pm[:, ct_key, :][:, dom_vw[:, 1]]
    cok = ((cw >> dom_vb[None, :, 1]) & np.uint32(1)).astype(bool)
    return zok & cok


def eligible_partition(st, result: SolveResult):
    """Partition the solved batch for the rung.

    Returns ``(elig, freed, lifted, seats)``: the group indexes with
    lifted pods, the freed solver-proposed node names the rung may
    re-pack, ``lifted[gi] -> [pods]`` — exactly the pods the rung
    re-seats — and ``seats[node] -> {gi: pods}`` over the freed nodes
    (the scan-solution warm start ``x0`` derives from it).

    A group is STATICALLY eligible iff it is unconstrained (no spread /
    hostname cap / (anti-)affinity slots, no volume or daemonset
    coupling, every available zone+capacity-type domain allowed — no
    pinning) and UNWATCHED (no constraint selector of any group matches
    its pods — the PR-6 coupling-guard condition: re-seating a watched
    pod silently changes someone else's spread count).  A node is freed
    iff EVERY pod seated on it belongs to a statically-eligible group (a
    mixed node stays whole — its constrained pods are boundary conditions
    and lifting only its unconstrained pods would strand slack the cost
    compare can't win back).  The rung lifts exactly the pods on freed
    nodes: eligible pods backfilled onto constrained or existing nodes
    keep their seats, so constraint-bearing placements are never
    disturbed and partial lifts stay sound by construction."""
    G = st.G
    pod_group: Dict[str, int] = {}
    for gi, g in enumerate(st.groups):
        for p in g.pods:
            pod_group[p.name] = gi

    watched = (np.asarray(st.g_sel_match).any(axis=0)
               if st.S else np.zeros(G, dtype=bool))
    dom_ok = _host_dom_ok(st)
    avail_dom = np.asarray(st.cand_avail).any(axis=0)  # [D]

    static_ok = np.zeros(G, dtype=bool)
    for gi, g in enumerate(st.groups):
        rep = g.pods[0]
        if (st.g_zone_spread[gi] >= 0 or st.g_host_spread[gi] >= 0
                or st.g_zone_anti[gi] >= 0 or st.g_zone_paff[gi] >= 0
                or st.g_host_paff[gi] >= 0 or bool(watched[gi])):
            continue
        if rep.volume_claims or rep.volume_zone_requirements or rep.is_daemon:
            continue
        if gang_fixed(rep):
            # gang members are relax-INELIGIBLE (ISSUE 20): their scan
            # seats are fixed boundary conditions the gang epilogue audits
            # and packs — the rung must not move them out from under it
            continue
        if not bool(np.all(dom_ok[gi] | ~avail_dom)):
            continue  # zone/ct pinning: the node's domain choice couples
        static_ok[gi] = True

    freed: Set[str] = set()
    lifted: Dict[int, List] = {}
    seats: Dict[str, Dict[int, int]] = {}  # freed node -> {gi: pods}
    for n in result.nodes:
        gis = []
        ok = True
        for q in n.pods:
            gi = pod_group.get(q.name)
            if gi is None or not static_ok[gi]:
                ok = False  # carve-out or constrained pod pins the node
                break
            gis.append(gi)
        if not ok:
            continue
        freed.add(n.name)
        cnt: Dict[int, int] = {}
        for gi, q in zip(gis, n.pods):
            lifted.setdefault(gi, []).append(q)
            cnt[gi] = cnt.get(gi, 0) + 1
        seats[n.name] = cnt
    return set(lifted), freed, lifted, seats


# ---------------------------------------------------------------------------
# rounding + repair
# ---------------------------------------------------------------------------


def _largest_remainder(row: np.ndarray, total: int) -> np.ndarray:
    """Integerize a non-negative row to the exact total, largest
    fractional parts first."""
    base = np.floor(row).astype(np.int64)
    delta = total - int(base.sum())
    if delta > 0:
        frac = row - base
        for i in np.argsort(-frac)[:delta]:
            base[i] += 1
    elif delta < 0:
        frac = row - base
        order = [i for i in np.argsort(frac) if base[i] > 0]
        for i in order[: -delta]:
            base[i] -= 1
    return base


def _prefix_fit(res_mat: np.ndarray, req: np.ndarray, k: int):
    """First-fit ``k`` identical pods with request ``req`` into the node
    residual rows ``res_mat`` in order (the PR-6 warm-start host tier's
    vectorized prefix allocation).  Returns (takes[N], placed)."""
    if not len(res_mat) or k <= 0:
        return np.zeros(len(res_mat), dtype=np.int64), 0
    pos = req > 0
    with np.errstate(divide="ignore", invalid="ignore"):
        cap = np.floor(np.min(
            np.where(pos[None, :],
                     (res_mat + 1e-9) / np.maximum(req[None, :], 1e-12),
                     np.inf),
            axis=1))
    cap = np.where(np.isfinite(cap), np.maximum(cap, 0.0), float(k))
    before = np.cumsum(cap) - cap
    takes = np.clip(k - before, 0.0, cap).astype(np.int64)
    return takes, int(takes.sum())


class _Rounding:
    """Mutable state of the integral build: the open node fleet (one
    residual row per node), assignments, and provisioner-limit usage."""

    def __init__(self, st, prov_used: np.ndarray) -> None:
        self.st = st
        self.prov_used = prov_used                  # [P, R] mutable
        self.node_cand: List[int] = []              # candidate per node
        self.node_res: List[np.ndarray] = []        # residual per node
        self.takes: List[Tuple[int, int, int]] = []  # (gi, node_idx, k)
        self.cost = 0.0

    def limit_headroom(self, ci: int) -> int:
        p = int(self.st.cand_prov[ci])
        cap_row = np.asarray(self.st.cand_cap[ci], dtype=np.float64)
        head = np.asarray(self.st.prov_limits[p], dtype=np.float64) \
            - self.prov_used[p]
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(cap_row > 0,
                           np.floor((head + 1e-6) / np.maximum(cap_row, 1e-12)),
                           np.inf)
        n = np.min(per)
        return int(n) if np.isfinite(n) else (1 << 30)

    def buy(self, ci: int, n: int, price: float) -> List[int]:
        p = int(self.st.cand_prov[ci])
        self.prov_used[p] += np.asarray(self.st.cand_cap[ci],
                                        dtype=np.float64) * n
        idxs = []
        alloc = np.asarray(self.st.cand_alloc[ci], dtype=np.float64)
        for _ in range(n):
            idxs.append(len(self.node_res))
            self.node_cand.append(ci)
            self.node_res.append(alloc.copy())
        self.cost += price * n
        return idxs

    def fill(self, gi: int, node_idxs: Sequence[int], k: int) -> int:
        """First-fit k pods of group gi into the given nodes; returns the
        number placed."""
        if not node_idxs or k <= 0:
            return 0
        req = np.asarray(self.st.requests[gi], dtype=np.float64)
        res_mat = np.stack([self.node_res[i] for i in node_idxs])
        takes, placed = _prefix_fit(res_mat, req, k)
        for j, ni in enumerate(node_idxs):
            if takes[j] > 0:
                self.node_res[ni] = res_mat[j] - req * takes[j]
                self.takes.append((gi, ni, int(takes[j])))
        return placed


def _sparsify(x: np.ndarray, counts: np.ndarray, feas: np.ndarray,
              req: np.ndarray, alloc_inv: np.ndarray,
              frac: float = 0.05, rounds: int = 3) -> np.ndarray:
    """Concentrate the descent's interior point before integerizing.

    Entropic mirror descent converges to interior points that smear a few
    percent of every group across many near-optimal candidates; rounded
    literally, every touched candidate pays a partial last node and the
    integral cost explodes.  Two alternating prunes, renormalizing after
    each: (a) per GROUP, drop allocations under ``frac`` of the group
    (keeping its largest), (b) per CANDIDATE, drop candidates carrying
    less than ~one node's worth of total bottleneck load.  Each prune can
    only move mass onto candidates the descent already ranked higher, and
    the never-worse select downstream makes aggressiveness safe."""
    x = x.copy()
    for _ in range(rounds):
        keep = x >= frac * np.maximum(counts[:, None], 1.0)
        amax = x.argmax(axis=1)
        keep[np.arange(len(x)), amax] = True
        x = np.where(keep & feas, x, 0.0)
        y = ((x.T @ req) * alloc_inv).max(axis=1)    # fractional node count
        col_keep = y >= 0.9
        col_keep[x.argmax(axis=1)] = True            # every row keeps a home
        x = np.where(col_keep[None, :], x, 0.0)
        s = x.sum(axis=1, keepdims=True)
        x = np.where(s > 0, x / np.maximum(s, 1e-30), 0.0) * counts[:, None]
    return x


def _round_solution(st, x: np.ndarray, lift_counts: Dict[int, int],
                    prov_used: np.ndarray, F: np.ndarray):
    """Integral build from the fractional solution.

    Per group: largest-remainder split over its candidates.  Per
    candidate: buy the integral bottleneck node count and fill each node
    with the PROPORTIONAL group mix — node ``j`` takes
    ``round((j+1)*n_gc/N) - round(j*n_gc/N)`` pods of group ``g`` — which
    is what realizes the relaxation's complementary-resource pairing
    (group-sequential first-fit would exhaust one resource before the
    complementary group arrives and re-fragment into per-group fleets).
    Per-node integer jitter that overflows capacity is re-fit within the
    candidate, then stranded pods backfill cross-candidate.  Returns
    ``(rounding, leftovers{gi: count})``; None when a group has no
    purchasable candidate at all."""
    G, C = st.G, st.C
    x = np.maximum(np.asarray(x[:G, :C], dtype=np.float64), 0.0)

    pr = np.where(np.asarray(st.cand_avail), np.asarray(st.cand_price),
                  np.inf)
    p_c = pr.min(axis=1)                             # effective $/node

    n_alloc = np.zeros((G, C), dtype=np.int64)
    for gi in sorted(lift_counts):
        row = np.where(F[gi] & np.isfinite(p_c), x[gi], 0.0)
        total = int(lift_counts[gi])
        s = row.sum()
        if s <= 0:
            # descent starved the row (all-infeasible numerics): fall back
            # to the cheapest-density feasible candidate for the group
            ok = F[gi] & np.isfinite(p_c)
            if not ok.any():
                return None, {gi: total}
            req = np.asarray(st.requests[gi], dtype=np.float64)
            alloc = np.asarray(st.cand_alloc, dtype=np.float64)
            with np.errstate(divide="ignore", invalid="ignore"):
                ppn = np.min(np.where(req[None, :] > 0,
                                      np.floor(alloc / np.maximum(req[None, :],
                                                                  1e-12)),
                                      np.inf), axis=1)
            dens = np.where(ok & (ppn >= 1), p_c / np.maximum(ppn, 1.0),
                            np.inf)
            row = np.zeros(C)
            row[int(np.argmin(dens))] = 1.0
            s = 1.0
        n_alloc[gi] = _largest_remainder(row * (total / s), total)

    rounding = _Rounding(st, prov_used)
    leftovers: Dict[int, int] = {}
    order = [int(g) for g in np.argsort(-np.asarray(st.magnitude))]
    requests = np.asarray(st.requests, dtype=np.float64)
    for ci in range(C):
        col = n_alloc[:, ci]
        if col.sum() == 0:
            continue
        if not np.isfinite(p_c[ci]):
            for gi in np.nonzero(col)[0]:
                leftovers[gi] = leftovers.get(gi, 0) + int(col[gi])
            continue
        alloc_c = np.asarray(st.cand_alloc[ci], dtype=np.float64)
        load = requests.T @ col                       # [R]
        with np.errstate(divide="ignore", invalid="ignore"):
            per_r = np.where(alloc_c > 1e-9,
                             load / np.maximum(alloc_c, 1e-9), np.inf)
            per_r = np.where(load > 1e-9, per_r, 0.0)
        bottleneck = float(np.max(per_r))
        if not np.isfinite(bottleneck):
            for gi in np.nonzero(col)[0]:
                leftovers[gi] = leftovers.get(gi, 0) + int(col[gi])
            continue
        n_nodes = max(int(np.ceil(bottleneck)), 1)
        buy = min(n_nodes, rounding.limit_headroom(ci))
        cand_nodes = rounding.buy(ci, buy, float(p_c[ci])) if buy else []
        overflow: Dict[int, int] = {}
        placed_col = np.zeros(G, dtype=np.int64)
        if buy:
            # vectorized proportional quotas: node j of the fleet takes
            # round((j+1)*n_g/buy) - round(j*n_g/buy) pods of group g —
            # telescopes to exactly n_g, never more than ±1 off the real-
            # valued per-node mix the bottleneck guarantees fits
            used_g = np.nonzero(col)[0]
            n_g = col[used_g].astype(np.float64)
            steps = np.arange(buy + 1, dtype=np.float64)[:, None]
            cum = np.rint(steps * n_g[None, :] / buy)
            quota = (cum[1:] - cum[:-1]).astype(np.int64)   # [buy, |used|]
            load = quota @ requests[used_g]                 # [buy, R]
            fits = np.all(load <= alloc_c[None, :] + 1e-9, axis=1)
            for j in np.nonzero(~fits)[0]:
                # integer jitter overflowed this node: sequential re-take
                # in FFD-magnitude order, overflow re-queued below
                res = alloc_c.copy()
                for oi in sorted(range(len(used_g)),
                                 key=lambda i: order.index(int(used_g[i]))):
                    t = int(quota[j, oi])
                    if t <= 0:
                        continue
                    req_g = requests[used_g[oi]]
                    pos = req_g > 0
                    with np.errstate(divide="ignore", invalid="ignore"):
                        cap = np.min(np.where(
                            pos, (res + 1e-9) / np.maximum(req_g, 1e-12),
                            np.inf))
                    take = int(min(t, max(int(cap), 0)))
                    quota[j, oi] = take
                    res -= req_g * take
                load[j] = quota[j] @ requests[used_g]
            for j, ni in enumerate(cand_nodes):
                rounding.node_res[ni] = alloc_c - load[j]
            nz_j, nz_i = np.nonzero(quota)
            for j, oi in zip(nz_j.tolist(), nz_i.tolist()):
                gi = int(used_g[oi])
                k = int(quota[j, oi])
                rounding.takes.append((gi, cand_nodes[j], k))
                placed_col[gi] += k
        for gi in np.nonzero(col)[0]:
            short = int(col[gi]) - int(placed_col[gi])
            if short > 0:
                overflow[int(gi)] = overflow.get(int(gi), 0) + short
        # re-fit integer jitter within the candidate's own fleet first,
        # then fund the straggler tail with extra whole nodes (the ceil
        # bottleneck is exact in aggregate; ±1-pod-per-group-per-node
        # jitter can exceed it by a node or two at scale)
        for gi in list(overflow):
            placed = rounding.fill(gi, cand_nodes, overflow[gi])
            overflow[gi] -= placed
            k = overflow[gi]
            if k > 0:
                req_g = requests[gi]
                pos = req_g > 0
                with np.errstate(divide="ignore", invalid="ignore"):
                    ppn = np.min(np.where(pos, np.floor(
                        (alloc_c + 1e-6) / np.maximum(req_g, 1e-12)),
                        np.inf))
                if np.isfinite(ppn) and ppn >= 1:
                    extra = min(int(np.ceil(k / ppn)),
                                rounding.limit_headroom(ci))
                    if extra > 0:
                        new_idxs = rounding.buy(ci, extra, float(p_c[ci]))
                        cand_nodes.extend(new_idxs)
                        k -= rounding.fill(gi, new_idxs, k)
            if k > 0:
                leftovers[gi] = leftovers.get(gi, 0) + k

    # cross-candidate backfill: stranded pods take any open rounded
    # capacity on a candidate their group is feasible for
    if leftovers and rounding.node_res:
        for gi in sorted(leftovers):
            ok_nodes = [i for i, ci in enumerate(rounding.node_cand)
                        if F[gi, ci]]
            placed = rounding.fill(gi, ok_nodes, leftovers[gi])
            leftovers[gi] -= placed
        leftovers = {gi: k for gi, k in leftovers.items() if k > 0}
    return rounding, leftovers


def _materialize(st, rounding: _Rounding,
                 lifted: Dict[int, List]) -> Tuple[List[SimNode],
                                                   Dict[str, str]]:
    """SimNodes + assignments from the rounded build (same construction
    as the scan's extraction, solver/tpu.py _extract).  Pods come from
    the partition's lifted pools — the exact pods taken off the freed
    nodes, never a group-mate that kept its seat."""
    pr = np.where(np.asarray(st.cand_avail), np.asarray(st.cand_price),
                  np.inf)
    d_c = pr.argmin(axis=1)
    n_ct = max(1, len(st.ct_names))
    nodes: List[SimNode] = []
    for ci in rounding.node_cand:
        prov_name, type_name = st.cand_names[ci]
        di = int(d_c[ci])
        zone = st.zone_names[int(st.dom_zone[di])] if st.zone_names else ""
        node = SimNode(
            instance_type=type_name,
            provisioner=prov_name,
            zone=zone,
            capacity_type=st.ct_names[di % n_ct] if st.ct_names else "",
            price=float(pr[ci, di]),
            allocatable={
                st.vocab.resources[r]: float(st.cand_alloc[ci, r])
                for r in range(st.cand_alloc.shape[1])
            },
            existing=False,
        )
        node.stamp_labels()
        nodes.append(node)

    per_group: Dict[int, List[Tuple[int, int]]] = {}
    for gi, ni, k in rounding.takes:
        per_group.setdefault(gi, []).append((ni, k))
    assignments: Dict[str, str] = {}
    for gi, picks in per_group.items():
        pods = lifted[gi]
        pos = 0
        for ni, k in picks:
            chunk = pods[pos:pos + k]
            pos += k
            name = nodes[ni].name
            nodes[ni].pods.extend(chunk)
            assignments.update((p.name, name) for p in chunk)
    return nodes, assignments


def _self_validate(st, lift_counts: Dict[int, int], rounding: _Rounding,
                   leftovers: Optional[Dict[int, int]] = None) -> bool:
    """Cheap integrality/capacity audit of the rounded fleet, at group
    granularity (no per-pod walk): every lifted pod placed exactly once
    OR accounted in ``leftovers`` (the repair hook's input), and every
    rounded node's take-derived load within its candidate allocatable.
    Runs BEFORE repair — an overloaded rounded node handed to the repair
    solve as a seed would ship (the scan sees negative residual and just
    places nothing more there).  A failed audit falls back to the scan —
    never ships."""
    G = st.G
    leftovers = leftovers or {}
    placed = np.zeros(G, dtype=np.int64)
    load = np.zeros((len(rounding.node_res), st.requests.shape[1]),
                    dtype=np.float64)
    requests = np.asarray(st.requests, dtype=np.float64)
    for gi, ni, k in rounding.takes:
        placed[gi] += k
        load[ni] += requests[gi] * k
    for gi in range(G):
        want = int(lift_counts.get(gi, 0)) - int(leftovers.get(gi, 0))
        if placed[gi] != want:
            return False
    alloc = np.asarray(st.cand_alloc, dtype=np.float64)
    for ni, ci in enumerate(rounding.node_cand):
        if np.any(load[ni] > alloc[ci] + 1e-6):
            return False
    return True


# ---------------------------------------------------------------------------
# the rung
# ---------------------------------------------------------------------------


def refine(
    result: SolveResult,
    st,
    *,
    registry: Optional[Registry] = None,
    guard=None,
    trace=None,
    repair_solve=None,
    relax_iters: Optional[int] = None,
) -> Tuple[SolveResult, str]:
    """Run the relaxation rung over a scan result and ship the cheaper of
    {scan, relax+round}.  Returns ``(result, outcome)`` with outcome in
    RELAX_OUTCOMES; on every outcome except "improved" the input result is
    returned unchanged.  ``repair_solve(pods, seed_nodes)`` (optional) is
    the integrality repair hook: a full scheduler re-solve of the stranded
    pods SEEDED with the rounded fleet as existing-node state.  The caller
    owns readiness (``relax_signature`` must be warm) and policy routing;
    this function owns the math and the never-worse select."""
    t0 = time.perf_counter()
    registry = registry or default_registry
    trace = trace or NULL_TRACE
    iters = iter_rung(configured_iters() if relax_iters is None
                      else relax_iters)
    with trace.span("relax") as span:
        try:
            out, outcome, ratio = _refine_inner(
                result, st, guard=guard, repair_solve=repair_solve,
                iters=iters)
        # ktlint: allow[KT005] the rung is an optimization layer: any
        # failure ships the proven scan solution and counts as fallback
        except Exception:
            logger.warning("relax rung failed; scan solution ships",
                           exc_info=True)
            out, outcome, ratio = result, "fallback", None
        span.annotate(outcome=outcome,
                      ratio=None if ratio is None else round(ratio, 4))
    record_outcome(registry, outcome,
                   seconds=time.perf_counter() - t0, ratio=ratio)
    return out, outcome


def _refine_inner(result: SolveResult, st, *, guard, repair_solve,
                  iters: int):
    elig, freed, lifted, seats = eligible_partition(st, result)
    if not elig or not freed:
        return result, "skipped", None

    F = _host_feasibility(st)
    dims = relax_dims(st)
    Gp, Cp, R = dims["G"], dims["C"], dims["R"]
    G, C = st.G, st.C

    lift_counts = {gi: len(pods) for gi, pods in lifted.items()}
    req = np.zeros((Gp, R), dtype=np.float32)
    req[:G] = st.requests
    counts = np.zeros(Gp, dtype=np.float32)
    for gi, k in lift_counts.items():
        counts[gi] = float(k)
    elig_mask = counts[:G] > 0

    pr = np.where(np.asarray(st.cand_avail), np.asarray(st.cand_price),
                  np.inf)
    p_c = pr.min(axis=1)
    feas = np.zeros((Gp, Cp), dtype=bool)
    feas[:G, :C] = F & elig_mask[:, None] & np.isfinite(p_c)[None, :]
    price = np.zeros(Cp, dtype=np.float32)
    price[:C] = np.where(np.isfinite(p_c), p_c, 0.0).astype(np.float32)

    alloc = np.asarray(st.cand_alloc, dtype=np.float32)
    alloc_inv = np.zeros((Cp, R), dtype=np.float32)
    with np.errstate(divide="ignore"):
        alloc_inv[:C] = np.where(alloc > 1e-9, 1.0 / np.maximum(alloc, 1e-9),
                                 0.0)

    # warm start from the scan's own solution (the freed nodes' seated
    # counts from the partition pass; + a uniform escape term so the
    # descent can leave the scan's vertex)
    cand_index = {pair: ci for ci, pair in enumerate(st.cand_names)}
    node_cand = {n.name: cand_index.get((n.provisioner, n.instance_type))
                 for n in result.nodes if n.name in freed}
    x0 = np.zeros((Gp, Cp), dtype=np.float32)
    for name, cnt in seats.items():
        ci = node_cand.get(name)
        if ci is None:
            continue
        for gi, k in cnt.items():
            if feas[gi, ci]:
                x0[gi, ci] += float(k)
    uni = feas[:G].astype(np.float32)
    usum = uni.sum(axis=1, keepdims=True)
    uni = np.where(usum > 0, uni / np.maximum(usum, 1.0), 0.0) \
        * counts[:G, None]
    x0[:G] = 0.7 * x0[:G] + 0.3 * uni

    bx, _bf = _run_relax(req, counts, feas, alloc_inv, price, x0, iters,
                         guard=guard)
    bx = _sparsify(np.asarray(bx, dtype=np.float64),
                   counts.astype(np.float64), feas,
                   req.astype(np.float64), alloc_inv.astype(np.float64))

    # kept fleet + provisioner usage base (limits bind on raw capacity,
    # matching the scan and the ground-truth validator)
    kept_new = [n for n in result.nodes if n.name not in freed]
    freed_nodes = [n for n in result.nodes if n.name in freed]
    P = len(st.prov_names)
    prov_index = {n: i for i, n in enumerate(st.prov_names)}
    prov_used = np.zeros((P, st.prov_limits.shape[1]), dtype=np.float64)
    for node in list(result.existing_nodes) + kept_new:
        pi = prov_index.get(node.provisioner)
        if pi is not None:
            prov_used[pi] += st.capacity_row(node.instance_type,
                                             node.allocatable)

    rounding, leftovers = _round_solution(st, bx, lift_counts, prov_used, F)
    if rounding is None:
        return result, "fallback", None
    if not _self_validate(st, lift_counts, rounding, leftovers):
        return result, "fallback", None
    nodes_new, assignments_new = _materialize(st, rounding, lifted)

    scan_cost = sum(n.price for n in result.nodes)
    repair_nodes: List[SimNode] = []
    repair_existing: Optional[List[SimNode]] = None
    if leftovers:
        if repair_solve is None:
            return result, "fallback", None
        # integrality repair: re-solve the stranded pods through the
        # existing scan, SEEDED from the rounded solution (the PR-6
        # warm-start shape: rounded + kept nodes are the existing-node
        # state, so the repair packs against everything already placed)
        stranded: List = []
        assigned_names = set(assignments_new)
        for gi, k in leftovers.items():
            pool = [p for p in lifted[gi] if p.name not in assigned_names]
            stranded.extend(pool[:k])
        seeds = list(result.existing_nodes) + kept_new + nodes_new
        sub = repair_solve(stranded, seeds)
        if sub is None or sub.infeasible:
            return result, "fallback", None
        placed = list(sub.existing_nodes)
        ne = len(result.existing_nodes)
        nk = len(kept_new)
        repair_existing = placed[:ne]
        kept_new = placed[ne:ne + nk]
        nodes_new = placed[ne + nk:]
        repair_nodes = list(sub.nodes)
        assignments_new.update(sub.assignments)

    relax_cost = (sum(n.price for n in kept_new)
                  + sum(n.price for n in nodes_new)
                  + sum(n.price for n in repair_nodes))
    ratio = relax_cost / scan_cost if scan_cost > 0 else 1.0
    if relax_cost >= scan_cost - 1e-9:
        return result, ("tied" if relax_cost <= scan_cost + 1e-9
                        else "fallback"), ratio

    # adopt: the rung's fleet replaces the freed nodes
    if repair_existing is not None:
        result.existing_nodes = repair_existing
    result.nodes = kept_new + nodes_new + repair_nodes
    result.assignments.update(assignments_new)
    logger.info(
        "relax rung improved the solve: %d eligible pods re-packed, "
        "node cost %.4f -> %.4f (%.2f%%)",
        sum(lift_counts.values()), scan_cost, relax_cost,
        100.0 * (1.0 - ratio))
    return result, "improved", ratio
