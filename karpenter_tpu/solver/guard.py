"""Hang protection for the in-process device tier.

The TPU is reached through a network tunnel, and round 5 observed its two
real failure modes live: a wedged tunnel whose device calls never return
(backend init still succeeds), and a dead relay that hangs even backend
initialization.  The gRPC solver sidecar already degrades through a health
gate (``service/client.py``: fall back to the local oracle, reconnect in
the background), but an operator running the device tier IN-PROCESS had no
equivalent — one hung solve wedged the whole reconcile loop forever, which
is strictly worse than the reference's Go controller can fail.

jax offers no deadline primitive — a hung PJRT call never returns to
bytecode — so the guard dispatches device calls on an expendable daemon
thread and abandons it on timeout:

- the device tier is latched **unhealthy** and the scheduler serves every
  subsequent batch from the warm host tiers (native C++ / CPU oracle, the
  same degradation contract as the remote client's health gate);
- a background probe thread re-runs a tiny device op until it answers,
  then re-enables the device tier;
- the abandoned call thread cannot be killed (it is blocked inside the
  PJRT C++ runtime); it is daemonized so it never pins process exit, and
  the unhealthy latch bounds the leak at one abandoned solve thread plus
  one probe thread per outage.

Snapshot isolation makes abandonment safe: solvers place pods on their own
snapshots of the caller's nodes (``SimNode.snapshot``, tested invariant),
so a timed-out solve that completes later mutates nothing the live
scheduler still reads.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

#: abandoned call threads, joined briefly at interpreter exit: a daemon
#: thread killed mid-XLA prints "FATAL: exception not rethrown" during
#: teardown — give a just-slow call a moment to drain, but never pin exit
#: on a truly wedged tunnel (that is the guard's whole point).
_ABANDONED: List[threading.Thread] = []
_EXIT_GRACE_S = 5.0


@atexit.register
def _drain_abandoned() -> None:
    deadline = _EXIT_GRACE_S
    for t in _ABANDONED:
        if deadline <= 0:
            break
        import time as _time

        # ktlint: allow[KT002] interpreter-exit drain deadline: runs from
        # atexit after the controllers (and their injected clocks) are gone,
        # and a fake-advanced clock must never shorten the real join grace
        t0 = _time.monotonic()
        t.join(deadline)
        deadline -= _time.monotonic() - t0  # ktlint: allow[KT002] see above

#: default guard timeout.  The guard covers only warm-tier device solves
#: (the ``auto`` policy never compiles inline — compile-behind serves cold
#: shapes from the host tiers), so legitimate calls finish in milliseconds
#: to a few seconds; 180 s is two orders of magnitude of margin while still
#: unwedging a dead tunnel in bounded time.  Override with
#: ``KT_DEVICE_SOLVE_TIMEOUT_S``; 0 disables the guard.
DEFAULT_TIMEOUT_S = 180.0


class DeviceHang(Exception):
    """A guarded device call exceeded its deadline (wedged tunnel?)."""


def _default_probe() -> None:
    import jax.numpy as jnp

    jnp.zeros(4).sum().block_until_ready()


class DeviceGuard:
    def __init__(
        self,
        timeout_s: Optional[float] = None,
        probe_interval_s: float = 30.0,
        probe_fn: Callable[[], None] = _default_probe,
        on_health_change: Optional[Callable[[bool], None]] = None,
    ) -> None:
        if timeout_s is None:
            timeout_s = float(
                os.environ.get("KT_DEVICE_SOLVE_TIMEOUT_S", DEFAULT_TIMEOUT_S)
            )
        self.timeout_s = timeout_s
        self.probe_interval_s = probe_interval_s
        self.probe_fn = probe_fn
        self.on_health_change = on_health_change
        self._lock = threading.Lock()
        self._healthy = True
        self._probing = False
        self._stop = threading.Event()

    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0

    @property
    def healthy(self) -> bool:
        return self._healthy

    def run(self, fn, *args, **kwargs):
        """Run ``fn`` with the hang deadline; raise :class:`DeviceHang` on
        timeout (latching unhealthy), else return/raise exactly what ``fn``
        did."""
        return self._run(self.timeout_s, fn, args, kwargs)

    def run_budgeted(self, fn, *args, budget_frac: float = 1.0, **kwargs):
        """Like :meth:`run` with ``budget_frac`` of the deadline.  The
        hierarchical solver dispatches up to ``1 + KT_HIER_PRICE_ITERS``
        block waves per batch; splitting the whole-solve deadline across
        them keeps a wedged tunnel latching in the same bounded time as one
        flat solve instead of ``waves ×`` longer."""
        frac = min(max(budget_frac, 0.0), 1.0)
        return self._run(self.timeout_s * frac, fn, args, kwargs)

    def _run(self, timeout_s: float, fn, args, kwargs):
        if not self.enabled or timeout_s <= 0:
            return fn(*args, **kwargs)
        box: dict = {}
        done = threading.Event()

        def work():
            try:
                box["val"] = fn(*args, **kwargs)
            # ktlint: allow[KT005] the expendable call thread boxes EVERY
            # outcome (incl. KeyboardInterrupt) and run() re-raises it on
            # the caller thread — swallowing here would turn a device error
            # into a phantom hang
            except BaseException as e:  # noqa: BLE001 — re-raised in caller
                box["err"] = e
            finally:
                done.set()

        t = threading.Thread(target=work, daemon=True, name="kt-device-call")
        t.start()
        if not done.wait(timeout_s):
            _ABANDONED.append(t)
            self._mark_unhealthy()
            raise DeviceHang(
                f"device call exceeded {timeout_s:.0f}s; device tier "
                "latched unhealthy (warm host tiers serve until a probe "
                "succeeds)"
            )
        if "err" in box:
            raise box["err"]
        return box["val"]

    def stop(self) -> None:
        """Stop the recovery probe (operator shutdown)."""
        self._stop.set()

    # ---- internals -----------------------------------------------------
    def _mark_unhealthy(self) -> None:
        with self._lock:
            if not self._healthy:
                return
            self._healthy = False
            start_probe = not self._probing
            self._probing = True
            # callback under the lock: a recovery racing this transition
            # must not interleave its on_health_change(True) after ours and
            # leave the health gauge reading 1 through a real outage
            if self.on_health_change:
                self.on_health_change(False)
        logger.error(
            "device tier UNHEALTHY: a device call hung past %.0fs; solves "
            "degrade to the warm host tiers until a probe succeeds",
            self.timeout_s,
        )
        if start_probe:
            threading.Thread(
                target=self._probe_loop, daemon=True, name="kt-device-probe"
            ).start()

    def _probe_loop(self) -> None:
        # The probe op runs inline in this thread: if the device is still
        # wedged the op blocks HERE (no new probe threads pile up), and when
        # the tunnel unwedges the blocked op completes and recovery follows
        # on the next iteration — hung-then-recovered needs no extra timer.
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe_fn()
            except Exception as e:  # noqa: BLE001 — probe failure = still down
                logger.debug("device probe failed: %r", e)
                continue
            with self._lock:
                self._healthy = True
                self._probing = False
                if self.on_health_change:
                    self.on_health_change(True)  # under the lock, see above
            logger.info(
                "device tier RECOVERED: probe op answered; device solves "
                "re-enabled"
            )
            return
