"""Cost-neutral node coalescing — merge small new nodes into larger types.

The scan-over-groups solver buys each group's tail residue at that group's
step, so two groups can each buy a half-size node where the sequential
oracle's pod-interleaved first-fit would have filled one larger node
(BASELINE config 5: +24 mid-size nodes at equal-or-lower $).  Node count is
real operational load — kubelet/API traffic, image pulls, ENI/IP slots,
interruption exposure — so after extraction the solver merges same-
(provisioner, zone, capacity-type) NEW nodes into one larger catalog type
whenever:

- the larger type's allocatable fits the combined used resources (including
  the pod-density row), and
- its price is <= the sum of the replaced nodes' prices (NEVER spends $ —
  in-family pricing is linear, so 2x 4xlarge -> 1x 8xlarge is exact), and
- the provisioner either has no finite limits or the replacement's raw
  capacity does not exceed the replaced capacity (limits bind on capacity),
  and
- no group in the solve carries hostname-scoped constraints (hostname
  anti-affinity/spread caps are per-NODE: merging two nodes that each hold
  one matching pod would co-locate them; zone-scoped constraints are safe —
  merging preserves the zone).

Greedy smallest-first within each bucket; deterministic.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .types import SimNode

#: prov_limits entries at/above this are "no limit" sentinels
_NO_LIMIT = 3.0e37
#: pair scan covers only this many smallest nodes per bucket (fragments
#: cluster at the small end; bounds host time on large solves)
FRAG_WINDOW = 64


def label_feasibility(st) -> np.ndarray:
    """Host-side [G, C] label/provisioner feasibility — the numpy mirror of
    the device precompute (tpu.compute_feasibility's gather branch): group g's
    packed requirement mask admits candidate c's label values, and the
    group tolerates/fits the candidate's provisioner.  Merge targets must be
    feasible for every group with pods on the merged node — the solve
    honored F, coalescing must too (a node_selector pinned to one instance
    type must never be merged onto another).  Cached on the tensors."""
    cached = getattr(st, "_host_F", None)
    if cached is not None:
        return cached
    pm = np.asarray(st.pm)                    # [G, K, W] uint32
    vw = np.asarray(st.cand_vw)               # [C, K]
    vb = np.asarray(st.cand_vb).astype(np.uint32)
    kc = np.asarray(st.key_check)             # [K]
    G, K, _W = pm.shape
    C = vw.shape[0]
    lab = np.ones((G, C), dtype=bool)
    for k in range(K):
        if not kc[k]:
            continue
        words = pm[:, k, :][:, vw[:, k]]      # [G, C]
        lab &= ((words >> vb[None, :, k]) & 1).astype(bool)
    gp_ok = np.asarray(st.gp_ok)
    lab &= gp_ok[np.arange(G)[:, None], np.asarray(st.cand_prov)[None, :]]
    st._host_F = lab
    return lab


def hostname_constrained(st) -> bool:
    """Any group whose constraints are scoped to individual nodes — merging
    nodes could violate them, so coalescing is skipped for the whole solve
    when per-node group tracking is unavailable."""
    return bool(
        (np.asarray(st.g_host_spread) >= 0).any()
        or (np.asarray(st.g_host_paff) >= 0).any()
        or (np.asarray(st.g_host_cap) > 0).any()
    )


def hostname_capped_groups(st) -> set:
    """Group indices whose hostname rules CAP pods per node (spread maxSkew,
    anti-affinity) — a merge combining two nodes' counts can violate these,
    so nodes holding them are frozen out of coalescing.  Positive hostname
    affinity (g_host_paff) is NOT capping: it wants matching pods together,
    and merging only ever adds pods to a node, so it cannot break (fuzz
    seed 23: one paff group used to disable coalescing for the whole solve,
    stranding mergeable fragments in every other group)."""
    return set(np.flatnonzero(np.asarray(st.g_host_spread) >= 0).tolist())


def _pkey(a: SimNode, b: SimNode) -> tuple:
    """Order-free identity key for the symmetric pair-feasibility cache."""
    ia, ib = id(a), id(b)
    return (ia, ib) if ia < ib else (ib, ia)


def _domain_index(st, zone: str, ct: str) -> Optional[int]:
    try:
        zi = st.zone_names.index(zone)
        ci = st.ct_names.index(ct)
    except ValueError:
        return None
    return zi * max(1, len(st.ct_names)) + ci


def apply_coalesce(st, nodes, used_rows, node_groups, assignments):
    """Shared tier epilogue: run the merge pass and repoint assignments of
    absorbed nodes at their replacements.  Both the device tier
    (tpu._extract) and the native tier (native.solve_tensors_native) call
    this so the cold-start answer and the warm answer stay the same
    coalescing contract."""
    if len(nodes) < 2:
        return nodes
    nodes, renames = coalesce_new_nodes(st, nodes, used_rows,
                                        node_groups=node_groups)
    if renames:
        for pod_name, node_name in list(assignments.items()):
            if node_name in renames:
                assignments[pod_name] = renames[node_name]
    return nodes


def coalesce_new_nodes(
    st,
    nodes: List[SimNode],
    used_rows: Dict[int, np.ndarray],  # id(node) -> used resource row [R]
    node_groups: Optional[Dict[int, set]] = None,  # id(node) -> {group idx}
) -> Tuple[List[SimNode], Dict[str, str]]:
    """Merge mergeable new nodes; returns (new node list, renames) where
    ``renames`` maps absorbed old node names -> their replacement's name.
    Pods are moved onto the replacement nodes; callers fix assignments via
    the rename map.  ``node_groups`` scopes the label-feasibility check to
    the groups actually placed on each node; without it (untracked solves)
    the merge target must be feasible for EVERY group in the solve."""
    capped = hostname_capped_groups(st)
    if node_groups is None:
        # untracked solves can't scope the check per node: all-or-nothing
        if hostname_constrained(st):
            return nodes, {}
        capped = set()
    # per-node hostname bookkeeping for capped solves: a merge is legal when,
    # for every hostname slot either node's groups cap, the COMBINED count of
    # slot-matching pods stays within the stricter cap (anti-affinity
    # cap 1/0, spread maxSkew).  Group labels are uniform, so counts come
    # from g_sel_match at group granularity — no per-pod selector matching.
    # This is what lets bench config 3 (every pod hostname-anti) coalesce its
    # 1-pod-per-service fragments into shared nodes at equal-or-lower price.
    g_hs = np.asarray(st.g_host_spread)
    g_hc = np.asarray(st.g_host_cap)
    host_active = bool(capped) and (g_hs >= 0).any()
    pod_group: Dict[str, int] = {}
    if host_active:
        for gi, g in enumerate(st.groups):
            for p in g.pods:
                pod_group[p.name] = gi
    S_all = st.g_sel_match.shape[0]

    def _host_state(n: SimNode):
        """(counts[S], caps[S]) for one node; caps inf where unconstrained."""
        cnt = np.zeros(S_all, dtype=np.int64)
        cap = np.full(S_all, np.inf)
        for p in n.pods:
            gi = pod_group.get(p.name)
            if gi is None:
                # a pod outside this solve (shouldn't happen for new nodes):
                # be conservative, forbid merging this node
                cap[:] = -1.0
                return cnt, cap
            cnt += st.g_sel_match[:, gi]
            s = int(g_hs[gi])
            if s >= 0:
                cap[s] = min(cap[s], float(g_hc[gi]))
            # positive hostname affinity (g_host_paff) needs no cap: it wants
            # matching pods together, and merging only ever ADDS co-residents
        return cnt, cap
    F = label_feasibility(st)                             # [G, C]
    all_groups = frozenset(range(F.shape[0]))

    # candidate rows by provisioner, cheapest-capacity order is not needed:
    # we pick the cheapest feasible replacement by price
    by_prov: Dict[str, List[int]] = {}
    for ci, (prov, _it) in enumerate(st.cand_names):
        by_prov.setdefault(prov, []).append(ci)
    prov_index = {n: i for i, n in enumerate(st.prov_names)}

    buckets: Dict[tuple, List[SimNode]] = {}
    for n in nodes:
        buckets.setdefault((n.provisioner, n.zone, n.capacity_type), []).append(n)

    out: List[SimNode] = []
    renames: Dict[str, str] = {}
    for (prov, zone, ct), group in buckets.items():
        di = _domain_index(st, zone, ct)
        pi = prov_index.get(prov)
        cands = by_prov.get(prov, [])
        if di is None or pi is None or len(group) < 2 or not cands:
            out.extend(group)
            continue
        limited = bool((np.asarray(st.prov_limits)[pi] < _NO_LIMIT).any())
        # bucket-local candidate table (spot pricing is NOT linear in size —
        # zonal discounts vary per type — so the cheapest feasible
        # replacement can come from any family)
        cand_ix = np.asarray([ci for ci in cands if st.cand_avail[ci, di]],
                             dtype=np.int64)
        if cand_ix.size == 0:
            out.extend(group)
            continue
        c_alloc = np.asarray(st.cand_alloc)[cand_ix]          # [K, R]
        c_cap = np.asarray(st.cand_cap)[cand_ix]              # [K, R]
        c_price = np.asarray(st.cand_price)[cand_ix, di]      # [K]
        c_F = F[:, cand_ix]                                   # [G, K]

        def groups_of(n: SimNode) -> frozenset:
            if node_groups is None:
                return all_groups
            return frozenset(node_groups.get(id(n), all_groups))

        _hstate: Dict[int, tuple] = {}

        def host_state(n: SimNode) -> tuple:
            got = _hstate.get(id(n))
            if got is None:
                got = _host_state(n)
                _hstate[id(n)] = got
            return got

        def order_nodes(lst: List[SimNode]) -> List[SimNode]:
            """Scan order.  Plain solves: smallest-first.  Hostname-capped
            solves: same, but round-robin across group combinations — the
            solver creates one group's fragments consecutively, so a
            smallest-first window would fill with ONE service's nodes, whose
            pairs all violate the per-node cap; rotating group combos puts
            mergeable cross-service partners inside the window."""
            base = sorted(lst, key=lambda n: (size_of(n), n.name))
            if not host_active:
                return base
            seen: Dict[frozenset, int] = {}
            ranked = []
            for n in base:
                key = frozenset(groups_of(n))
                r = seen.get(key, 0)
                seen[key] = r + 1
                ranked.append((r, size_of(n), n.name, n))
            ranked.sort(key=lambda t: t[:3])
            return [t[3] for t in ranked]

        # per-node precomputes, cached by identity (merged nodes get entries
        # as they're created): candidate-feasibility row (AND over the node's
        # groups — c_F[union].all == c_F[a].all & c_F[b].all, so pair
        # feasibility is a cheap elementwise AND) and the raw-capacity row
        # for limit-bound buckets
        c_F_all = c_F.all(axis=0)
        _nF: Dict[int, np.ndarray] = {}
        _ncap: Dict[int, np.ndarray] = {}

        def node_F(n: SimNode) -> np.ndarray:
            got = _nF.get(id(n))
            if got is None:
                gs = groups_of(n)
                got = c_F_all if gs == all_groups else c_F[sorted(gs)].all(axis=0)
                _nF[id(n)] = got
            return got

        def node_cap(n: SimNode) -> np.ndarray:
            got = _ncap.get(id(n))
            if got is None:
                got = st.capacity_row(n.instance_type, n.allocatable)
                _ncap[id(n)] = got
            return got

        # smallest-first pair scan: any pair may merge (a cpu-heavy and a
        # mem-heavy fragment can share one node even when two same-size
        # fragments can't), so failure of one pair doesn't end the bucket.
        # The scan is windowed to the FRAG_WINDOW smallest nodes — fragments
        # live at the small end, and an unwindowed pair scan over a 50k-pod
        # solve's hundreds of nodes would cost more host time than the solve.
        # Pair feasibility is symmetric and unaffected by OTHER merges, so
        # it's cached by node-identity pair and evaluated in one batched
        # numpy pass per scan (the round-4 cold-path regression was this
        # loop in per-pair Python).  Merge order is unchanged: first
        # (i, then smallest j) feasible pair, cheapest candidate, resort,
        # rescan.
        pair_best: Dict[tuple, Optional[tuple]] = {}  # (ida,idb) -> (price,k)|None
        partners: Dict[int, set] = {}  # node id -> ids with a feasible merge
        _seen: set = set()           # node ids whose window pairs are cached
        _size: Dict[int, float] = {}  # node id -> used magnitude (sort key)
        _pinned: List[SimNode] = []  # absorbed nodes held alive: cache keys are
        # id()s — a GC'd node's id could be reused by a later merged node

        def size_of(n: SimNode) -> float:
            got = _size.get(id(n))
            if got is None:
                got = float(used_rows[id(n)].sum())
                _size[id(n)] = got
            return got

        def eval_pairs(window: List[SimNode]) -> None:
            """Fill pair_best for every uncached pair in the window.  Only
            pairs touching a node new to the window since the last eval can
            be uncached (pair feasibility is unaffected by other merges), so
            enumeration is O(new x W), not O(W^2) per scan."""
            w = len(window)
            new_ix = [i for i in range(w) if id(window[i]) not in _seen]
            if not new_ix:
                return
            new_set = set(new_ix)
            fresh = []
            for i in new_ix:
                for j in range(w):
                    if j == i or (j in new_set and j < i):
                        continue
                    a, b = (i, j) if i < j else (j, i)
                    if _pkey(window[a], window[b]) not in pair_best:
                        fresh.append((a, b))
            _seen.update(id(window[i]) for i in new_ix)
            if not fresh:
                return
            ai = np.asarray([i for i, _ in fresh])
            bj = np.asarray([j for _, j in fresh])
            used_w = np.stack([used_rows[id(n)] for n in window])     # [W,R]
            price_w = np.asarray([n.price for n in window])
            F_w = np.stack([node_F(n) for n in window])               # [W,K]
            need = used_w[ai] + used_w[bj]                            # [P,R]
            ok = F_w[ai] & F_w[bj]                                    # [P,K]
            R = need.shape[1]
            for r in range(R):
                ok &= c_alloc[None, :, r] + 1e-6 >= need[:, r, None]
            ok &= c_price[None, :] <= (price_w[ai] + price_w[bj])[:, None] + 1e-9
            if limited:
                cap_w = np.stack([node_cap(n) for n in window])
                capb = cap_w[ai] + cap_w[bj]
                for r in range(R):
                    ok &= c_cap[None, :, r] <= capb[:, r, None] + 1e-6
            if host_active:
                # hostname caps: combined slot-matching counts must respect
                # the stricter of the two nodes' caps on every slot
                hcnt = np.stack([host_state(n)[0] for n in window])  # [W,S]
                hcap = np.stack([host_state(n)[1] for n in window])  # [W,S]
                pair_ok = (
                    hcnt[ai] + hcnt[bj]
                    <= np.minimum(hcap[ai], hcap[bj])
                ).all(axis=1)
                ok &= pair_ok[:, None]
            any_p = ok.any(axis=1)
            hits = np.flatnonzero(any_p)
            ks = np.empty(len(fresh), dtype=np.int64)
            if hits.size:
                ks[hits] = np.where(ok[hits], c_price[None, :], np.inf).argmin(axis=1)
            for p, (i, j) in enumerate(fresh):
                a, b = window[i], window[j]
                if any_p[p]:
                    pair_best[_pkey(a, b)] = (float(c_price[ks[p]]), int(ks[p]))
                    partners.setdefault(id(a), set()).add(id(b))
                    partners.setdefault(id(b), set()).add(id(a))
                else:
                    pair_best[_pkey(a, b)] = None

        group = order_nodes(group)
        while len(group) >= 2:
            win = min(len(group), FRAG_WINDOW)
            window = group[:win]
            eval_pairs(window)
            hit = None
            for i in range(win - 1):
                ps = partners.get(id(window[i]))
                if not ps:
                    continue
                for j in range(i + 1, win):
                    if id(window[j]) in ps:
                        best = pair_best[_pkey(window[i], window[j])]
                        hit = (i, j, best[1],
                               used_rows[id(window[i])] + used_rows[id(window[j])])
                        break
                if hit is not None:
                    break
            if hit is None:
                break
            i, j, k, need = hit
            a, b = group[i], group[j]
            _pinned.extend((a, b))
            ci = int(cand_ix[k])
            _prov, type_name = st.cand_names[ci]
            node = SimNode(
                instance_type=type_name,
                provisioner=prov,
                zone=zone,
                capacity_type=ct,
                price=float(c_price[k]),
                allocatable={
                    st.vocab.resources[r]: float(st.cand_alloc[ci, r])
                    for r in range(st.cand_alloc.shape[1])
                },
                existing=False,
            )
            node.stamp_labels()
            node.pods = list(a.pods) + list(b.pods)
            used_rows[id(node)] = need
            _nF[id(node)] = node_F(a) & node_F(b)
            if host_active:
                ca, pa = host_state(a)
                cb, pb = host_state(b)
                _hstate[id(node)] = (ca + cb, np.minimum(pa, pb))
            if node_groups is not None:
                node_groups[id(node)] = set(groups_of(a) | groups_of(b))
            renames[a.name] = node.name
            renames[b.name] = node.name
            # an absorbed node may itself be a prior replacement:
            # forward earlier renames pointing at it
            for old, tgt in list(renames.items()):
                if tgt in (a.name, b.name):
                    renames[old] = node.name
            # absorbed nodes leave the partner graph (their ids must not
            # surface as hits in later scans)
            for gone in (id(a), id(b)):
                for other in partners.pop(gone, ()):  # symmetric cleanup
                    partners.get(other, set()).discard(gone)
            group = order_nodes(
                [n for idx, n in enumerate(group) if idx not in (i, j)] + [node]
            )
        out.extend(group)
    return out, renames
