"""Cost-neutral node coalescing — merge small new nodes into larger types.

The scan-over-groups solver buys each group's tail residue at that group's
step, so two groups can each buy a half-size node where the sequential
oracle's pod-interleaved first-fit would have filled one larger node
(BASELINE config 5: +24 mid-size nodes at equal-or-lower $).  Node count is
real operational load — kubelet/API traffic, image pulls, ENI/IP slots,
interruption exposure — so after extraction the solver merges same-
(provisioner, zone, capacity-type) NEW nodes into one larger catalog type
whenever:

- the larger type's allocatable fits the combined used resources (including
  the pod-density row), and
- its price is <= the sum of the replaced nodes' prices (NEVER spends $ —
  in-family pricing is linear, so 2x 4xlarge -> 1x 8xlarge is exact), and
- the provisioner either has no finite limits or the replacement's raw
  capacity does not exceed the replaced capacity (limits bind on capacity),
  and
- no group in the solve carries hostname-scoped constraints (hostname
  anti-affinity/spread caps are per-NODE: merging two nodes that each hold
  one matching pod would co-locate them; zone-scoped constraints are safe —
  merging preserves the zone).

Greedy smallest-first within each bucket; deterministic.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .types import SimNode

#: prov_limits entries at/above this are "no limit" sentinels
_NO_LIMIT = 3.0e37
#: pair scan covers only this many smallest nodes per bucket (fragments
#: cluster at the small end; bounds host time on large solves)
FRAG_WINDOW = 64


def label_feasibility(st) -> np.ndarray:
    """Host-side [G, C] label/provisioner feasibility — the numpy mirror of
    the device precompute (tpu.compute_feasibility's gather branch): group g's
    packed requirement mask admits candidate c's label values, and the
    group tolerates/fits the candidate's provisioner.  Merge targets must be
    feasible for every group with pods on the merged node — the solve
    honored F, coalescing must too (a node_selector pinned to one instance
    type must never be merged onto another).  Cached on the tensors."""
    cached = getattr(st, "_host_F", None)
    if cached is not None:
        return cached
    pm = np.asarray(st.pm)                    # [G, K, W] uint32
    vw = np.asarray(st.cand_vw)               # [C, K]
    vb = np.asarray(st.cand_vb).astype(np.uint32)
    kc = np.asarray(st.key_check)             # [K]
    G, K, _W = pm.shape
    C = vw.shape[0]
    lab = np.ones((G, C), dtype=bool)
    for k in range(K):
        if not kc[k]:
            continue
        words = pm[:, k, :][:, vw[:, k]]      # [G, C]
        lab &= ((words >> vb[None, :, k]) & 1).astype(bool)
    gp_ok = np.asarray(st.gp_ok)
    lab &= gp_ok[np.arange(G)[:, None], np.asarray(st.cand_prov)[None, :]]
    st._host_F = lab
    return lab


def hostname_constrained(st) -> bool:
    """Any group whose constraints are scoped to individual nodes — merging
    nodes could violate them, so coalescing is skipped for the whole solve."""
    return bool(
        (np.asarray(st.g_host_spread) >= 0).any()
        or (np.asarray(st.g_host_paff) >= 0).any()
        or (np.asarray(st.g_host_cap) > 0).any()
    )


def _domain_index(st, zone: str, ct: str) -> Optional[int]:
    try:
        zi = st.zone_names.index(zone)
        ci = st.ct_names.index(ct)
    except ValueError:
        return None
    return zi * max(1, len(st.ct_names)) + ci


def apply_coalesce(st, nodes, used_rows, node_groups, assignments):
    """Shared tier epilogue: run the merge pass and repoint assignments of
    absorbed nodes at their replacements.  Both the device tier
    (tpu._extract) and the native tier (native.solve_tensors_native) call
    this so the cold-start answer and the warm answer stay the same
    coalescing contract."""
    if len(nodes) < 2:
        return nodes
    nodes, renames = coalesce_new_nodes(st, nodes, used_rows,
                                        node_groups=node_groups)
    if renames:
        for pod_name, node_name in list(assignments.items()):
            if node_name in renames:
                assignments[pod_name] = renames[node_name]
    return nodes


def coalesce_new_nodes(
    st,
    nodes: List[SimNode],
    used_rows: Dict[int, np.ndarray],  # id(node) -> used resource row [R]
    node_groups: Optional[Dict[int, set]] = None,  # id(node) -> {group idx}
) -> Tuple[List[SimNode], Dict[str, str]]:
    """Merge mergeable new nodes; returns (new node list, renames) where
    ``renames`` maps absorbed old node names -> their replacement's name.
    Pods are moved onto the replacement nodes; callers fix assignments via
    the rename map.  ``node_groups`` scopes the label-feasibility check to
    the groups actually placed on each node; without it (untracked solves)
    the merge target must be feasible for EVERY group in the solve."""
    if hostname_constrained(st):
        return nodes, {}
    F = label_feasibility(st)                             # [G, C]
    all_groups = frozenset(range(F.shape[0]))

    # candidate rows by provisioner, cheapest-capacity order is not needed:
    # we pick the cheapest feasible replacement by price
    by_prov: Dict[str, List[int]] = {}
    for ci, (prov, _it) in enumerate(st.cand_names):
        by_prov.setdefault(prov, []).append(ci)
    prov_index = {n: i for i, n in enumerate(st.prov_names)}

    buckets: Dict[tuple, List[SimNode]] = {}
    for n in nodes:
        buckets.setdefault((n.provisioner, n.zone, n.capacity_type), []).append(n)

    out: List[SimNode] = []
    renames: Dict[str, str] = {}
    for (prov, zone, ct), group in buckets.items():
        di = _domain_index(st, zone, ct)
        pi = prov_index.get(prov)
        cands = by_prov.get(prov, [])
        if di is None or pi is None or len(group) < 2 or not cands:
            out.extend(group)
            continue
        limited = bool((np.asarray(st.prov_limits)[pi] < _NO_LIMIT).any())
        # bucket-local candidate table (spot pricing is NOT linear in size —
        # zonal discounts vary per type — so the cheapest feasible
        # replacement can come from any family)
        cand_ix = np.asarray([ci for ci in cands if st.cand_avail[ci, di]],
                             dtype=np.int64)
        if cand_ix.size == 0:
            out.extend(group)
            continue
        c_alloc = np.asarray(st.cand_alloc)[cand_ix]          # [K, R]
        c_cap = np.asarray(st.cand_cap)[cand_ix]              # [K, R]
        c_price = np.asarray(st.cand_price)[cand_ix, di]      # [K]
        c_F = F[:, cand_ix]                                   # [G, K]

        def groups_of(n: SimNode) -> frozenset:
            if node_groups is None:
                return all_groups
            return frozenset(node_groups.get(id(n), all_groups))

        def best_merge(a: SimNode, b: SimNode):
            need = used_rows[id(a)] + used_rows[id(b)]
            budget = a.price + b.price
            ok = (c_price <= budget + 1e-9) & (
                (c_alloc + 1e-6 >= need).all(axis=1)
            )
            # the solve honored F[g, c]; the merge target must too, for
            # every group with pods on either node (a node_selector pinned
            # to one instance type must never be merged onto another)
            gs = groups_of(a) | groups_of(b)
            if gs:
                ok &= c_F[sorted(gs)].all(axis=0)
            if limited:
                cap_budget = (st.capacity_row(a.instance_type, a.allocatable)
                              + st.capacity_row(b.instance_type, b.allocatable))
                ok &= (c_cap <= cap_budget + 1e-6).all(axis=1)
            if not ok.any():
                return None
            k = int(np.where(ok, c_price, np.inf).argmin())
            return float(c_price[k]), int(cand_ix[k]), need

        # smallest-first pair scan: any pair may merge (a cpu-heavy and a
        # mem-heavy fragment can share one node even when two same-size
        # fragments can't), so failure of one pair doesn't end the bucket.
        # The scan is windowed to the FRAG_WINDOW smallest nodes — fragments
        # live at the small end, and an unwindowed pair scan over a 50k-pod
        # solve's hundreds of nodes would cost more host time than the solve
        group = sorted(group, key=lambda n: (float(used_rows[id(n)].sum()), n.name))
        merged = True
        while merged and len(group) >= 2:
            merged = False
            win = min(len(group), FRAG_WINDOW)
            for i in range(win - 1):
                for j in range(i + 1, win):
                    hit = best_merge(group[i], group[j])
                    if hit is None:
                        continue
                    price, ci, need = hit
                    a, b = group[i], group[j]
                    _prov, type_name = st.cand_names[ci]
                    node = SimNode(
                        instance_type=type_name,
                        provisioner=prov,
                        zone=zone,
                        capacity_type=ct,
                        price=price,
                        allocatable={
                            st.vocab.resources[r]: float(st.cand_alloc[ci, r])
                            for r in range(st.cand_alloc.shape[1])
                        },
                        existing=False,
                    )
                    node.pods = list(a.pods) + list(b.pods)
                    used_rows[id(node)] = need
                    if node_groups is not None:
                        node_groups[id(node)] = set(groups_of(a) | groups_of(b))
                    renames[a.name] = node.name
                    renames[b.name] = node.name
                    # an absorbed node may itself be a prior replacement:
                    # forward earlier renames pointing at it
                    for old, tgt in list(renames.items()):
                        if tgt in (a.name, b.name):
                            renames[old] = node.name
                    group = sorted(
                        [n for k, n in enumerate(group) if k not in (i, j)]
                        + [node],
                        key=lambda n: (float(used_rows[id(n)].sum()), n.name),
                    )
                    merged = True
                    break
                if merged:
                    break
        out.extend(group)
    return out, renames
