"""Warm-start delta solving — steady-state reconcile as an incremental update.

A steady-state reconcile differs from the previous solve by a handful of
pods, yet the solver always re-runs the full scan over every group
(ROADMAP open item 4).  CvxCluster (PAPERS.md) gets its 100-1000x from
exploiting exactly this perturbation structure: reuse the previous
assignment, solve only the *displaced* subproblem, and fall back to the
full solve when the perturbation is too large or couples into placements
the incremental step cannot legally keep.

Three tiers, cheapest first (``DeltaOutcome.mode``):

- **noop / host** — removals are pure bookkeeping; unconstrained added pods
  first-fit into the surviving nodes' residual capacity with a vectorized
  numpy pass (label/taint compatibility via ``node_classes`` memoization,
  resources via one ``[N, R]`` residual matrix carried incrementally across
  the delta chain).  Sub-millisecond on the CPU dev host — the steady-state
  p50 the bench gates (``measure_warmstart``).
- **scan** — displaced pods that carry their own constraints (or need new
  nodes) are solved by the regular device scan *seeded from the previous
  assignment*: the subproblem's existing-node tensors (residuals, selector
  counts, zone counters, provisioner usage) ARE the previous solution, so
  spread/affinity against already-placed pods is enforced exactly.
- **full** — the perturbation exceeds ``KT_DELTA_MAX_FRAC`` of the cluster's
  pods, or a parity guard trips: a surviving pod's spread/affinity selector
  matches a displaced pod of a *different* group (the incremental step
  cannot see that constraint), or ANY selector-watched pod is removed —
  own group included, since the remaining placements may then sit outside
  a spread band only a re-solve can restore — and the whole problem
  re-solves from the stripped base state.  Guards are deliberately
  conservative: falling back costs latency, never correctness.

Cost parity vs the from-scratch solve is pinned by ``scripts/fuzz_sweep.py
--delta`` (random add/remove/ICE chains) and gated in ``bench.py`` at the
existing ``cost_ratio <= 1.02`` ceiling.  When the perturbation is disjoint
(no displaced pod interacts with a surviving placement), untouched
assignments are byte-identical to the previous solve BY CONSTRUCTION — the
incremental step never moves a pod it did not have to.

Ownership contract: ``delta_solve`` CONSUMES ``prev`` — the surviving node
objects and the assignments dict are carried into the returned result (and
mutated) rather than copied, so a 50k-pod chain step stays sub-millisecond.
Callers that need the old result must snapshot it first.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..metrics import (
    WARMSTART_DISPLACED,
    WARMSTART_DURATION,
    WARMSTART_SOLVES,
    Registry,
    registry as default_registry,
)
from ..gang import gang_fixed
from ..models import labels as L
from ..models.pod import PodSpec
from .types import SimNode, SolveResult, node_classes

logger = logging.getLogger(__name__)

#: delta-size ceiling: a perturbation displacing/removing more than this
#: fraction of the cluster's solved pods falls back to the full solve (the
#: incremental win only exists while the delta is small; a half-rebuilt
#: cluster deserves a fresh pack)
DELTA_MAX_FRAC = float(os.environ.get("KT_DELTA_MAX_FRAC", "0.05"))

#: absolute floor under the fractional threshold: tiny clusters (tests,
#: fresh deployments) still take the incremental paths for single-digit
#: deltas instead of falling back at 5% of 20 pods
DELTA_MIN_PODS = int(os.environ.get("KT_DELTA_MIN_PODS", "8"))

#: delta modes, in escalation order — also the zero-inited label population
#: of karpenter_solver_warmstart_solves_total (KT003)
DELTA_MODES = ("noop", "host", "scan", "full")


def zero_init_metrics(registry: Registry) -> None:
    """Register the warm-start series at 0 so rate()/increase() never lose
    the first delta (KT003)."""
    for mode in DELTA_MODES:
        if not registry.counter(WARMSTART_SOLVES).has({"mode": mode}):
            registry.counter(WARMSTART_SOLVES).inc({"mode": mode}, value=0.0)
    registry.histogram(WARMSTART_DURATION)
    registry.histogram(WARMSTART_DISPLACED)


@dataclass
class DeltaOutcome:
    """One ``delta_solve`` step: the updated result plus how it was served."""

    result: SolveResult
    mode: str                 # noop | host | scan | full
    displaced: int            # pods the step had to (re-)place
    removed: int              # pods the step unseated
    total_pods: int           # solved pods after the step
    solve_ms: float           # wall time of the step
    #: node names this step created / dropped, maintained INCREMENTALLY
    #: (O(delta), never a scan of the fleet) — the delta-serving reply
    #: builder reads these instead of diffing node sets per RPC.  Empty on
    #: mode="full": the whole solution was rebuilt, deltas are meaningless.
    created_nodes: List[str] = field(default_factory=list)
    pruned_nodes: List[str] = field(default_factory=list)

    @property
    def fell_back(self) -> bool:
        return self.mode == "full"


@dataclass
class _Meta:
    """Incremental bookkeeping carried across a delta chain on the result
    object (``result._warmstart_meta``): the surviving nodes in creation
    order, their residual-capacity matrix, and the constraint selectors of
    seated pods (the coupling guard's index).  Rebuilding it is O(cluster);
    maintaining it is O(delta)."""

    nodes: List[SimNode]                  # existing nodes first, then proposals
    n_existing: int                       # split index into `nodes`
    node_idx: Dict[str, int]              # node name -> index
    res_names: List[str]                  # residual column vocabulary
    res_pos: Dict[str, int]
    residual: np.ndarray                  # [N, R] float64 remaining capacity
    #: distinct (selector, group_key) pairs over constraint-bearing seated
    #: pods — the guard that detects a surviving constraint coupling into
    #: the perturbation.  A set, not a list: a 5k-replica spread deployment
    #: contributes ONE entry, keeping the per-displaced-pod guard scan
    #: O(distinct selectors).  Removals leave stale entries (conservative:
    #: may force an unnecessary fallback, never an unsound host placement).
    sel_terms: Set[tuple] = field(default_factory=set)
    total_pods: int = 0
    #: accumulated ICE'd offerings ((instance_type, zone, capacity_type))
    unavailable: Set[tuple] = field(default_factory=set)
    #: pods a chain step failed to place (objects retained so removal /
    #: reclaim steps — the ones that free capacity or limit headroom — can
    #: re-offer them; pure adds never help an unplaced pod, so they skip
    #: the re-offer and keep the host fast path hot)
    unplaced: Dict[str, PodSpec] = field(default_factory=dict)
    #: node_classes memo per relevant-key set: (class key per node name,
    #: class representative list, per-requirement-signature ok rows)
    cls_cache: Dict[frozenset, dict] = field(default_factory=dict)
    #: pod name -> node name for pods PRE-SEATED on existing nodes (never
    #: in prev.assignments) — removals of those pods need the same
    #: bookkeeping as solver-assigned ones, not a silent no-op that
    #: diverges the chain's residual/total from the cluster
    preseated: Dict[str, str] = field(default_factory=dict)


def _pod_row(pod: PodSpec, res_pos: Dict[str, int]) -> Optional[np.ndarray]:
    """Pod requests as a residual-vocabulary row (pods column included), or
    None when the pod requests a resource outside the vocabulary."""
    row = np.zeros(len(res_pos), dtype=np.float64)
    for k, v in pod.requests.items():
        j = res_pos.get(k)
        if j is None:
            return None
        row[j] = v
    row[res_pos[L.RESOURCE_PODS]] = max(
        row[res_pos[L.RESOURCE_PODS]], 1.0)
    return row


def _constraint_sels(pod: PodSpec):
    """The selectors a seated pod's hard constraints watch (spread + pod
    (anti-)affinity) — what the coupling guard indexes."""
    for t in pod.topology_spread:
        yield t.label_selector
    for t in pod.affinity_terms:
        yield t.label_selector


def _has_constraints(pod: PodSpec) -> bool:
    return bool(pod.topology_spread or pod.affinity_terms
                or pod.preferred_affinity_terms)


def build_meta(prev: SolveResult, unavailable=None) -> _Meta:
    """O(cluster) rebuild of the chain bookkeeping from a plain result —
    paid once at chain start (or after a full fallback)."""
    nodes = list(prev.existing_nodes) + list(prev.nodes)
    res_names: List[str] = [L.RESOURCE_CPU, L.RESOURCE_MEMORY, L.RESOURCE_PODS]
    seen = set(res_names)
    for n in nodes:
        for p in n.pods:
            for k in p.requests:
                if k not in seen:
                    seen.add(k)
                    res_names.append(k)
    res_pos = {k: j for j, k in enumerate(res_names)}
    residual = np.zeros((len(nodes), len(res_names)), dtype=np.float64)
    sel_terms: Set[tuple] = set()
    preseated: Dict[str, str] = {}
    total = 0
    for i, n in enumerate(nodes):
        rem = n.remaining()
        for k, j in res_pos.items():
            residual[i, j] = rem.get(k, 0.0)
        for p in n.pods:
            total += 1
            if p.name not in prev.assignments:
                preseated[p.name] = n.name
            if p.topology_spread or p.affinity_terms:
                gk = p.group_key()
                for sel in _constraint_sels(p):
                    sel_terms.add((sel, gk))
    meta = _Meta(
        nodes=nodes, n_existing=len(prev.existing_nodes),
        node_idx={n.name: i for i, n in enumerate(nodes)},
        res_names=res_names, res_pos=res_pos, residual=residual,
        sel_terms=sel_terms, total_pods=total,
        unavailable=set(unavailable or ()),
        preseated=preseated,
    )
    return meta


def _matched_terms(meta: _Meta, pod: PodSpec) -> Tuple[bool, bool]:
    """(matched_by_own_group, matched_by_foreign_group) — whether any seated
    constraint selector watches this pod's labels."""
    own = foreign = False
    gk = None
    for sel, sel_gk in meta.sel_terms:
        if sel.matches(pod.labels):
            if gk is None:
                gk = pod.group_key()
            if sel_gk == gk:
                own = True
            else:
                foreign = True
                break
    return own, foreign


def _class_rows(meta: _Meta, pods: Sequence[PodSpec]):
    """Per-pod node-compatibility over the surviving fleet, memoized at
    (requirement signature x node class) like consolidation.compat_matrix.
    Returns ``ok[P, N]`` bool or None when any pod has OR'd terms (host path
    ineligible)."""
    relevant: Set[str] = set()
    sigs = []
    for p in pods:
        terms = p.scheduling_requirements()
        if len(terms) != 1:
            return None
        reqs = terms[0]
        sigs.append(((reqs.signature(), tuple(p.tolerations)), reqs,
                     tuple(p.tolerations)))
        relevant.update(reqs)
    rk = frozenset(relevant)
    cache = meta.cls_cache.get(rk)
    if cache is None or cache["n_nodes"] != len(meta.nodes):
        cls_idx, cls_rep = node_classes(meta.nodes, rk)
        cache = {"n_nodes": len(meta.nodes),
                 "cls_idx": np.asarray(cls_idx, dtype=np.int64),
                 "cls_rep": cls_rep, "rows": {}}
        meta.cls_cache[rk] = cache
    out = np.zeros((len(pods), len(meta.nodes)), dtype=bool)
    keys = []
    for pi, (key, reqs, tols) in enumerate(sigs):
        row = cache["rows"].get(key)
        if row is None:
            row = np.zeros(len(cache["cls_rep"]), dtype=bool)
            for c, rep in enumerate(cache["cls_rep"]):
                row[c] = (not any(t.blocks(tols) for t in rep.taints)
                          and reqs.compatible(rep.labels) is None)
            cache["rows"][key] = row
        out[pi] = row[cache["cls_idx"]]
        keys.append(key)
    return out, keys


def _drop_node(meta: _Meta, idx: int) -> None:
    """Remove a node row (reclaimed, or a proposal emptied by removals)."""
    if idx < meta.n_existing:
        meta.n_existing -= 1
    del meta.nodes[idx]
    meta.residual = np.delete(meta.residual, idx, axis=0)
    meta.node_idx = {n.name: i for i, n in enumerate(meta.nodes)}
    meta.cls_cache.clear()


def _append_node(meta: _Meta, node: SimNode) -> None:
    rem = node.remaining()
    row = np.array([rem.get(k, 0.0) for k in meta.res_names], dtype=np.float64)
    meta.nodes.append(node)
    meta.node_idx[node.name] = len(meta.nodes) - 1
    meta.residual = np.vstack([meta.residual, row[None, :]])
    meta.cls_cache.clear()


def delta_solve(
    prev: SolveResult,
    added: Sequence[PodSpec] = (),
    removed: Sequence[str] = (),
    iced: Sequence[object] = (),
    *,
    solve_displaced,
    solve_full,
    max_delta_frac: Optional[float] = None,
    registry: Optional[Registry] = None,
    unavailable=None,
    force_full: bool = False,
) -> DeltaOutcome:
    """One warm-started reconcile step.  ``added`` are new pods to place,
    ``removed`` are pod names leaving, ``iced`` entries are either
    ``(instance_type, zone, capacity_type)`` offerings newly unavailable or
    node NAMES reclaimed out from under the cluster (their pods displace).

    ``solve_displaced(pods, existing_nodes, unavailable)`` solves the
    displaced subproblem seeded by the surviving placements;
    ``solve_full(pods, existing_nodes, unavailable)`` is the fallback full
    solve against the stripped base state.  Both return a SolveResult.

    ``unavailable`` offerings accumulate onto the chain on EVERY step
    (same semantics as ``iced`` offering entries) — seeding the first
    step's bookkeeping and merging into it thereafter.

    ``force_full=True`` takes the full-fallback path unconditionally
    (after the removal/reclaim bookkeeping, so the re-solve sees the
    perturbed pod set): the delta-serving reseed path uses it when a
    catalog/price epoch bump invalidates every cost the chain was packed
    against — the re-solve from the stripped base keeps the session
    alive instead of cold-starting the client (docs/ARCHITECTURE.md
    round 14).
    """
    t0 = time.perf_counter()
    registry = registry or default_registry
    zero_init_metrics(registry)
    frac = DELTA_MAX_FRAC if max_delta_frac is None else max_delta_frac

    meta: Optional[_Meta] = getattr(prev, "_warmstart_meta", None)
    if meta is None:
        meta = build_meta(prev, unavailable=unavailable)
    elif unavailable:
        # per-call unavailability accumulates onto the chain exactly like
        # `iced` offerings — a warm-chain step must not silently ignore an
        # ICE the caller passed via the documented `unavailable=` param
        meta.unavailable.update(tuple(u) for u in unavailable)
    assignments = prev.assignments
    infeasible = prev.infeasible

    displaced: List[PodSpec] = list(added)
    reclaimed_pods: List[PodSpec] = []
    need_full = False
    created_nodes: List[str] = []
    pruned_nodes: List[str] = []

    # ---- iced: offerings and reclaimed nodes ---------------------------
    reclaim_names: List[str] = []
    for entry in iced:
        if isinstance(entry, str):
            reclaim_names.append(entry)
        else:
            meta.unavailable.add(tuple(entry))

    # ---- removals: pure bookkeeping ------------------------------------
    n_removed = 0
    maybe_emptied: Set[str] = set()  # proposal nodes that lost pods
    for name in removed:
        if name in infeasible:
            del infeasible[name]
            meta.unplaced.pop(name, None)
            continue
        # solver-assigned first, then pods PRE-SEATED on existing nodes
        # (never in assignments) — both get identical capacity/guard
        # bookkeeping, else the chain's residual silently diverges from
        # the cluster
        node_name = assignments.pop(name, None)
        if node_name is None:
            node_name = meta.preseated.pop(name, None)
        if node_name is None:
            continue
        n_removed += 1
        idx = meta.node_idx.get(node_name)
        if idx is None:
            continue
        node = meta.nodes[idx]
        for k, p in enumerate(node.pods):
            if p.name == name:
                # a constraint-watched removal breaks the incremental
                # invariant: the remaining placements may now sit outside a
                # spread band only a re-solve can restore
                if meta.sel_terms and any(
                    sel.matches(p.labels) for sel, _ in meta.sel_terms
                ):
                    need_full = True
                row = _pod_row(p, meta.res_pos)
                if row is not None:
                    meta.residual[idx] += row
                else:
                    need_full = True  # unknown resource: residual stale
                del node.pods[k]
                meta.total_pods -= 1
                if idx >= meta.n_existing and not node.pods:
                    maybe_emptied.add(node.name)
                break

    # ---- reclaimed nodes: displace their pods --------------------------
    for name in reclaim_names:
        idx = meta.node_idx.get(name)
        if idx is None:
            continue
        node = meta.nodes[idx]
        for p in node.pods:
            assignments.pop(p.name, None)
            meta.preseated.pop(p.name, None)
            meta.total_pods -= 1
            if p.is_daemon:
                # daemonsets recreate their pods wherever capacity lands;
                # the survivors' allocatable already carries the daemonset
                # overhead (same contract as the controller's what-ifs)
                continue
            if meta.sel_terms and any(
                sel.matches(p.labels) for sel, _ in meta.sel_terms
            ):
                need_full = True  # constraint-coupled displacement
            if _has_constraints(p):
                need_full = True  # its own constraints must re-solve globally
            reclaimed_pods.append(p)
        pruned_nodes.append(node.name)
        _drop_node(meta, idx)
    displaced = displaced + reclaimed_pods

    # drop proposal nodes the removals emptied (their cost is reclaimed).
    # Only nodes that LOST a pod this step can have emptied — tracked
    # above, so this stays O(delta): the delta-serving path calls this
    # per RPC and a scan of the whole proposal fleet would put an
    # O(cluster) pass under every sub-ms step.
    for name in maybe_emptied:
        idx = meta.node_idx.get(name)
        if idx is not None and idx >= meta.n_existing \
                and not meta.nodes[idx].pods:
            pruned_nodes.append(name)
            _drop_node(meta, idx)

    # removals / reclaims free capacity (and provisioner-limit headroom):
    # re-offer the pods earlier steps could not place — a full solve would
    # see them too, so skipping them here would silently under-schedule.
    # Deduped against the caller's own adds: a caller re-offering a
    # still-unplaced pod in `added` must not double it into the subproblem
    if (n_removed or reclaim_names) and meta.unplaced:
        offered = {p.name for p in displaced}
        displaced = displaced + [u for n, u in meta.unplaced.items()
                                 if n not in offered]
        meta.unplaced.clear()

    def _finish(result: SolveResult, mode: str, keep_meta: bool,
                total: Optional[int] = None) -> DeltaOutcome:
        if keep_meta:
            result._warmstart_meta = meta  # type: ignore[attr-defined]
        elif getattr(result, "_warmstart_meta", None) is not None:
            result._warmstart_meta = None  # type: ignore[attr-defined]
        ms = (time.perf_counter() - t0) * 1000.0
        registry.counter(WARMSTART_SOLVES).inc({"mode": mode})
        registry.histogram(WARMSTART_DURATION).observe(ms / 1000.0)
        registry.histogram(WARMSTART_DISPLACED).observe(len(displaced))
        return DeltaOutcome(
            result=result, mode=mode, displaced=len(displaced),
            removed=n_removed,
            total_pods=meta.total_pods if total is None else total,
            solve_ms=ms,
            created_nodes=[] if mode == "full" else created_nodes,
            pruned_nodes=[] if mode == "full" else pruned_nodes,
        )

    def _rewrap() -> SolveResult:
        """Fresh SolveResult over the (mutated, shared) chain containers."""
        return SolveResult(
            nodes=meta.nodes[meta.n_existing:],
            assignments=assignments,
            infeasible=infeasible,
            existing_nodes=meta.nodes[:meta.n_existing],
            solve_ms=0.0,
        )

    def _full() -> DeltaOutcome:
        # re-solve everything from the stripped base: original existing
        # nodes minus every solver-assigned pod, plus all solved pods —
        # including the pods earlier steps could not place (the re-offer
        # above only fires on removals; a full repack must not silently
        # drop them from the problem)
        all_pods: List[PodSpec] = list(displaced)
        seen = {p.name for p in all_pods}
        all_pods.extend(p for n, p in meta.unplaced.items() if n not in seen)
        base: List[SimNode] = []
        for i, n in enumerate(meta.nodes):
            if i < meta.n_existing:
                snap = n.snapshot()
                keep, mine = [], []
                for p in snap.pods:
                    (mine if p.name in assignments else keep).append(p)
                snap.pods = keep
                all_pods.extend(mine)
                base.append(snap)
            else:
                all_pods.extend(n.pods)
        result = solve_full(all_pods, base, set(meta.unavailable))
        return _finish(result, "full", keep_meta=False,
                       total=len(all_pods) - len(result.infeasible))

    # ---- threshold + coupling guards -----------------------------------
    total = meta.total_pods + len(displaced)
    if force_full or need_full or (displaced or n_removed) and (
        len(displaced) + n_removed
        > max(float(DELTA_MIN_PODS), frac * max(total, 1))
    ):
        return _full()

    if not displaced:
        return _finish(_rewrap(), "noop", keep_meta=True)

    # classify the displaced pods: host-eligible (no constraints of their
    # own, nothing watching them), scan (own constraints / own-group
    # coupling / needs a new node), or full (foreign coupling)
    host_ok = True
    for p in displaced:
        own, foreign = _matched_terms(meta, p)
        if foreign:
            return _full()
        # gang members never take the host fast path: only the scan
        # subproblem runs the gang epilogue, and the host first-fit could
        # otherwise seat an INCOMPLETE gang (short of its declared size)
        # with no all-or-nothing audit (ISSUE 20, docs/GANGS.md)
        if (own or _has_constraints(p) or p.volume_claims or p.is_daemon
                or gang_fixed(p)):
            host_ok = False

    if host_ok:
        rows = [_pod_row(p, meta.res_pos) for p in displaced]
        compat = None
        if all(r is not None for r in rows):
            compat = _class_rows(meta, displaced)
        if compat is not None:
            ok_pn, sig_keys = compat
            # group identical pods (same request row + same compat
            # signature) and place each group by one vectorized prefix
            # allocation over nodes in creation order — value-identical to
            # per-pod first-fit for interchangeable pods, one numpy pass
            # per GROUP instead of six ops per pod
            by_key: Dict[tuple, List[int]] = {}
            for i in range(len(displaced)):
                by_key.setdefault(
                    (rows[i].tobytes(), sig_keys[i]), []).append(i)
            order = sorted(
                by_key.items(),
                key=lambda kv: (-float(rows[kv[1][0]].sum()),
                                displaced[kv[1][0]].name),
            )
            res = meta.residual.copy()
            picks: List[Tuple[int, int]] = []
            fit_all = True
            for _key, idxs in order:
                row = rows[idxs[0]]
                ok = ok_pn[idxs[0]]
                pos = row > 0
                cap = np.floor(np.min(
                    np.where(pos[None, :],
                             (res + 1e-9) / np.maximum(row[None, :], 1e-12),
                             np.inf),
                    axis=1))
                cap = np.where(ok & (cap > 0), cap, 0.0)
                before = np.cumsum(cap) - cap
                take = np.clip(len(idxs) - before, 0.0, cap)
                if take.sum() < len(idxs) - 1e-9:
                    fit_all = False
                    break
                res -= row[None, :] * take[:, None]
                it = iter(idxs)
                for j in np.nonzero(take)[0]:
                    for _ in range(int(round(take[j]))):
                        picks.append((next(it), int(j)))
            if fit_all:
                meta.residual = res
                for i, j in picks:
                    pod, node = displaced[i], meta.nodes[j]
                    node.pods.append(pod)
                    assignments[pod.name] = node.name
                    infeasible.pop(pod.name, None)
                    # a caller-re-offered pod that now placed must leave
                    # the retention dict, or a later removal would
                    # re-offer (and double-seat) it again
                    meta.unplaced.pop(pod.name, None)
                meta.total_pods += len(displaced)
                return _finish(_rewrap(), "host", keep_meta=True)
            # some pod needs a new node: the scan decides which to buy

    # ---- scan: the displaced subproblem seeded from the previous
    # assignment (existing-node tensors ARE the previous solution)
    sub = solve_displaced(list(displaced), list(meta.nodes),
                          set(meta.unavailable))
    new_by_name = {n.name: n for n in sub.nodes}
    adopted: Dict[str, SimNode] = {}
    for p in displaced:
        target = sub.assignments.get(p.name)
        if target is None:
            infeasible[p.name] = sub.infeasible.get(
                p.name, "solver: no feasible placement")
            meta.unplaced[p.name] = p
            continue
        infeasible.pop(p.name, None)
        meta.unplaced.pop(p.name, None)  # placed: retention entry retired
        meta.total_pods += 1
        assignments[p.name] = target
        idx = meta.node_idx.get(target)
        if idx is not None:
            node = meta.nodes[idx]
            # by NAME, not identity: the scheduler hardens preference-
            # bearing pods (ScheduleAnyway spread, preferred affinity) via
            # copy before seating them, so the object on the node is a
            # copy of `p` — an identity check would re-append the original
            # and double-book the node
            seated = any(q.name == p.name for q in node.pods)
            if not seated:
                node.pods.append(p)
            if seated and target in adopted:
                # a node adopted THIS step got its residual row from
                # node.remaining(), which already accounts for every pod
                # the solver seated on it — subtracting again would
                # understate the node's slack for the rest of the chain
                pass
            else:
                row = _pod_row(p, meta.res_pos)
                if row is not None:
                    meta.residual[idx] -= row
                else:
                    # out-of-vocabulary resource: recompute the row exactly
                    # so a stale residual can never over-offer this node to
                    # a later host-path placement
                    rem = node.remaining()
                    meta.residual[idx] = [rem.get(k, 0.0)
                                          for k in meta.res_names]
        else:
            node = new_by_name.get(target)
            if node is not None and target not in adopted:
                adopted[target] = node
                created_nodes.append(target)
                _append_node(meta, node)
        if _has_constraints(p):
            gk = p.group_key()
            for sel in _constraint_sels(p):
                meta.sel_terms.add((sel, gk))
    result = _rewrap()
    result.solve_ms = sub.solve_ms
    return _finish(result, "scan", keep_meta=True)
