"""Gang & rank-aware scheduling — all-or-nothing pod groups (ISSUE 20).

TPU/MPI training ships as tightly-coupled pod groups that must place
atomically and close together ("Rank-Aware Resource Scheduling for
Tightly-Coupled MPI Workloads on Kubernetes", PAPERS.md).  Members of one
gang share a ``gang_id`` and carry the gang's declared ``gang_size``
(wire fields on pb.Pod; old bytes decode to ""/0 = ungrouped).  The
contract, enforced here and composed into every serving surface
(docs/GANGS.md):

- **All-or-nothing.**  A gang either FULLY places or contributes zero
  nodes: one infeasible member retracts every comember's seat, and every
  member surfaces as unplaced with the typed :class:`GangUnplaced`
  reason.  A partial gang placement is impossible by construction.
- **Rank/topology packing.**  Fully-placed gangs are judged on a spread
  penalty (distinct zones first, distinct node classes — the rack proxy
  — second) and re-packed onto co-located capacity when the combined
  node-cost + ``KT_GANG_SPREAD_WEIGHT x spread`` objective strictly
  improves; never-worse by construction, like the relax rung.
- **One unit everywhere.**  A gang is one admission ticket (a shed sheds
  the whole gang), one delta perturbation (an add places atomically or
  falls back to the full solve; a member removal retracts the gang), a
  hierarchy coupling component that is never split across blocks, a
  consolidation what-if unit (the entire gang re-seats or the candidate
  fails), and relax-rung ineligible (members keep their scan seats as
  fixed boundary conditions, like spread-constrained pods).

This package owns EVERY per-member gang judgement: ktlint KT025 flags
direct ``.gang_id`` / ``.gang_size`` access in admission// solver/ so
sanctioned entry points stay the helpers below.

``KT_GANG=0`` kills the whole subsystem: no epilogue, no retraction, no
coupling — byte-identical to pre-gang behavior.
"""

from __future__ import annotations

import copy
import logging
import os
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..metrics import (
    GANG_DURATION,
    GANG_GANGS,
    GANG_OUTCOMES,
    GANG_SPREAD_CLASSES,
    GANG_SPREAD_ZONES,
)
from ..models import labels as L
from ..models.pod import PodSpec

logger = logging.getLogger(__name__)

#: cross-node-class spread weighs a fraction of cross-zone spread: a gang
#: split across racks (node classes) inside one zone is closer than one
#: split across zones (the paper's rank-distance ordering)
CLASS_SPREAD_FRACTION = 0.1
#: co-location what-ifs attempted per spread-out gang (candidate zones,
#: best-first); bounds the epilogue at a few sequential oracle passes
MAX_PACK_CANDIDATES = 3


def gang_enabled() -> bool:
    """KT_GANG kill switch: default on; 0 restores pre-gang behavior
    byte-for-byte (no epilogue, no retraction, no coupling, no packing)."""
    return os.environ.get("KT_GANG", "1") != "0"


def spread_weight() -> float:
    """KT_GANG_SPREAD_WEIGHT: $/hr-equivalent charged per unit of gang
    spread (one unit = one extra zone; an extra node class costs
    CLASS_SPREAD_FRACTION of that).  The packing epilogue adopts a
    repack only when node-cost + weight x spread strictly improves."""
    try:
        return float(os.environ.get("KT_GANG_SPREAD_WEIGHT", "0.25"))
    except ValueError:
        return 0.25


class GangValidationError(ValueError):
    """A request's gang tagging is inconsistent (members of one gang_id
    disagree on gang_size, or a declared size is not positive).  Raised at
    the service entry point BEFORE admission — the gang is one ticket, so
    a malformed gang is refused whole (INVALID_ARGUMENT on the wire)."""


class GangUnplaced:
    """Typed unplaced reason for every member of a retracted gang.

    Stringifies into ``SolveResult.infeasible`` values (the reason dict is
    str -> str on the wire); :func:`is_gang_reason` recognizes the typed
    prefix so callers can branch without parsing prose.
    """

    PREFIX = "GangUnplaced"

    __slots__ = ("gang_id", "gang_size", "seated")

    def __init__(self, gang_id: str, gang_size: int, seated: int) -> None:
        self.gang_id = gang_id
        self.gang_size = gang_size
        self.seated = seated

    def __str__(self) -> str:
        return (
            f"{self.PREFIX}: gang '{self.gang_id}' could seat only "
            f"{self.seated}/{self.gang_size} members — all-or-nothing "
            "retracted every seat (a gang never places partially)"
        )

    @classmethod
    def is_gang_reason(cls, reason: str) -> bool:
        return isinstance(reason, str) and reason.startswith(cls.PREFIX)


def is_gang_reason(reason: str) -> bool:
    return GangUnplaced.is_gang_reason(reason)


# ---- membership helpers (the sanctioned per-member entry points) --------

def gang_of(pod: PodSpec) -> str:
    """The pod's gang id, "" for ungrouped — the one sanctioned attribute
    read serving code routes through (ktlint KT025)."""
    return getattr(pod, "gang_id", "") or ""


def gang_fixed(pod: PodSpec) -> bool:
    """True when the pod's seat is a fixed boundary condition for the
    relax rung (a gang member with the subsystem enabled)."""
    return gang_enabled() and bool(gang_of(pod))


def has_gangs(pods: Iterable[PodSpec]) -> bool:
    return any(gang_of(p) for p in pods)


def gang_members(pods: Iterable[PodSpec]) -> Dict[str, List[PodSpec]]:
    """gang_id -> members present in ``pods`` (insertion-ordered)."""
    out: Dict[str, List[PodSpec]] = {}
    for p in pods:
        gid = gang_of(p)
        if gid:
            out.setdefault(gid, []).append(p)
    return out


def declared_size(members: Sequence[PodSpec]) -> int:
    """The gang's declared size: the members' gang_size (validated equal),
    floored at the member count for robustness against 0/unset sizes."""
    declared = max((int(getattr(p, "gang_size", 0) or 0) for p in members),
                   default=0)
    return max(declared, len(members))


def validate_batch(pods: Iterable[PodSpec]) -> None:
    """Service-entry gang audit: every member of one gang_id must declare
    the same positive gang_size (or leave it unset).  Raises
    :class:`GangValidationError` — the gang is one admission ticket, so a
    malformed gang refuses whole before admission ever queues it."""
    if not gang_enabled():
        return
    sizes: Dict[str, int] = {}
    counts: Dict[str, int] = {}
    for p in pods:
        gid = gang_of(p)
        if not gid:
            continue
        size = int(getattr(p, "gang_size", 0) or 0)
        if size < 0:
            raise GangValidationError(
                f"gang '{gid}': member '{p.name}' declares negative "
                f"gang_size {size}")
        counts[gid] = counts.get(gid, 0) + 1
        if size:
            prev = sizes.setdefault(gid, size)
            if prev != size:
                raise GangValidationError(
                    f"gang '{gid}': members disagree on gang_size "
                    f"({prev} vs {size}) — a gang is judged whole and "
                    "must declare one size")
    for gid, n in counts.items():
        size = sizes.get(gid, 0)
        if size and n > size:
            raise GangValidationError(
                f"gang '{gid}': request carries {n} members but declares "
                f"gang_size {size}")


def admission_units(pods: Iterable[PodSpec]) -> int:
    """Admission-ticket count of a batch: each gang is ONE unit (classes/
    quotas judge it whole; a shed sheds the whole gang), singletons one
    each.  Pure accounting — the queue admits the request as one ticket
    either way; this is the number surfaced on traces/stats."""
    gangs: Set[str] = set()
    singles = 0
    for p in pods:
        gid = gang_of(p)
        if gid:
            gangs.add(gid)
        else:
            singles += 1
    return singles + len(gangs)


def zero_init_gang_metrics(registry) -> None:
    """KT003: the gang outcome series exist at zero from scheduler
    construction, guarded so re-construction over a shared registry never
    clobbers live counts."""
    c = registry.counter(GANG_GANGS)
    for outcome in GANG_OUTCOMES:
        if not c.has({"outcome": outcome}):
            c.inc({"outcome": outcome}, value=0.0)
    # unlabeled histograms: touching the family registers it so the
    # first real gang observation is rate()-visible
    registry.histogram(GANG_SPREAD_ZONES)
    registry.histogram(GANG_SPREAD_CLASSES)
    registry.histogram(GANG_DURATION)


# ---- placement audit ----------------------------------------------------

def _preseated_counts(result, batch_names: Set[str]) -> Dict[str, int]:
    """Members already seated on the result's nodes but NOT part of this
    batch, per gang — a delta/consolidation subproblem solves a gang
    subset while its comembers stay seated on existing capacity, and the
    all-or-nothing audit must count those seats."""
    out: Dict[str, int] = {}
    for n in list(result.existing_nodes) + list(result.nodes):
        for q in n.pods:
            gid = gang_of(q)
            if gid and q.name not in batch_names:
                out[gid] = out.get(gid, 0) + 1
    return out


def _watched(retracted: Sequence[PodSpec], result) -> bool:
    """Whether removing ``retracted`` seats could disturb someone else's
    constraint accounting: any REMAINING pod carries a spread or
    (anti-)affinity selector matching a retracted pod's labels.  When
    true, the in-place retraction is unsafe (removing a counted pod can
    strand a spread band mid-hole) and the caller re-solves instead."""
    gone = {p.name for p in retracted}
    labels = [p.labels for p in retracted]
    for n in list(result.existing_nodes) + list(result.nodes):
        for q in n.pods:
            if q.name in gone:
                continue
            for tsc in q.topology_spread:
                if any(tsc.label_selector.matches(lb) for lb in labels):
                    return True
            for term in q.affinity_terms:
                if any(term.label_selector.matches(lb) for lb in labels):
                    return True
    return False


def _retract_seats(result, members: Sequence[PodSpec]) -> None:
    """Unseat ``members`` in place: pop assignments, drop the pod objects
    from their nodes, and drop solver-proposed nodes left without a
    non-daemon pod (the gang bought them; retraction returns them)."""
    names = {p.name for p in members}
    touched: Set[str] = set()
    by_name = {n.name: n for n in list(result.nodes) + list(result.existing_nodes)}
    for p in members:
        node_name = result.assignments.pop(p.name, None)
        if node_name is None:
            continue
        node = by_name.get(node_name)
        if node is not None:
            node.pods = [q for q in node.pods if q.name not in names]
            touched.add(node.name)
    result.nodes = [
        n for n in result.nodes
        if n.name not in touched or any(not q.is_daemon for q in n.pods)
    ]


# ---- the solve epilogue -------------------------------------------------

def _gang_nodes(result, members: Sequence[PodSpec]) -> Optional[List]:
    """The node objects hosting every member, or None if any member's
    assignment points at a node the result no longer carries."""
    by_name = {n.name: n for n in list(result.nodes) + list(result.existing_nodes)}
    out = []
    for p in members:
        node = by_name.get(result.assignments.get(p.name, ""))
        if node is None:
            return None
        out.append(node)
    return out


def _spread(nodes: Sequence) -> Tuple[int, int, float]:
    """(zones, node_classes, penalty) of a fully-placed gang's seats."""
    zones = {n.zone for n in nodes}
    classes = {n.instance_type for n in nodes}
    penalty = (len(zones) - 1) + CLASS_SPREAD_FRACTION * (len(classes) - 1)
    return len(zones), len(classes), penalty


def _member_zones(members: Sequence[PodSpec], zone_names: Sequence[str]) -> List[str]:
    """Zones every member may legally land in (node_selector pin ANDed
    with volume zone requirements) — the co-location candidates."""
    allowed = list(zone_names)
    for p in members:
        pin = p.node_selector.get(L.ZONE)
        if pin is not None:
            allowed = [z for z in allowed if z == pin]
        for r in p.volume_zone_requirements:
            vs = r.value_set()
            allowed = [z for z in allowed if vs.contains(z)]
    return allowed


def _try_pack(result, gid: str, members: Sequence[PodSpec], *,
              provisioners, instance_types, daemonsets, unavailable,
              allow_new_nodes, max_new_nodes, in_band: Callable,
              old_penalty: float) -> bool:
    """One gang's co-location repack: what-if the members pinned to each
    candidate zone against everything else placed, adopt the first
    strictly-cheaper (node cost + weighted spread) answer.  Never-worse
    by construction — rejection keeps the valid incumbent."""
    from ..solver.reference import solve as oracle_solve

    w = spread_weight()
    if w <= 0.0:
        return False
    # a hard zone-spread member makes co-location ILLEGAL, not just a
    # what-if the oracle can veto: the pinned copy's selector narrows its
    # eligible-zone set to the pin (skew trivially satisfied in the
    # sub-solve), but the ORIGINAL pod restored after adoption is judged
    # over the full eligible set — packing would ship a skew violation
    if any(t.topology_key == L.ZONE and t.when_unsatisfiable == "DoNotSchedule"
           for p in members for t in p.topology_spread):
        return False
    zone_names: List[str] = []
    for it in instance_types:
        for o in it.offerings:
            if o.zone not in zone_names:
                zone_names.append(o.zone)
    allowed = _member_zones(members, zone_names)
    if not allowed:
        return False

    names = {p.name for p in members}
    base = []
    emptied_price = {}
    n_existing = len(result.existing_nodes)
    for i, n in enumerate(list(result.existing_nodes) + list(result.nodes)):
        s = n.snapshot()
        s.pods = [q for q in s.pods if q.name not in names]
        base.append(s)
        if i >= n_existing and not any(not q.is_daemon for q in s.pods):
            emptied_price[s.name] = s.price
    # candidate order: where the gang already sits (fewest moves), then by
    # free capacity proxy (node count) — bounded attempts
    seat_zone: Dict[str, int] = {}
    for p in members:
        node = next((n for n in list(result.nodes) + list(result.existing_nodes)
                     if n.name == result.assignments.get(p.name)), None)
        if node is not None:
            seat_zone[node.zone] = seat_zone.get(node.zone, 0) + 1
    candidates = sorted(
        allowed, key=lambda z: (-seat_zone.get(z, 0), z))[:MAX_PACK_CANDIDATES]

    budget = (None if max_new_nodes is None
              else max(0, max_new_nodes - len(result.nodes)))
    for z in candidates:
        pinned = []
        for p in members:
            q = copy.copy(p)
            q.node_selector = dict(p.node_selector)
            q.node_selector[L.ZONE] = z
            q.__dict__.pop("_group_key", None)
            pinned.append(q)
        try:
            sub = oracle_solve(
                pinned, provisioners, instance_types,
                existing_nodes=base, daemonsets=daemonsets,
                unavailable=unavailable, allow_new_nodes=allow_new_nodes,
                max_new_nodes=budget,
            )
        # ktlint: allow[KT005] a failed what-if keeps the valid incumbent —
        # the packing rung is strictly opportunistic
        except Exception:
            logger.debug("gang %s: co-location what-if for zone %s failed",
                         gid, z, exc_info=True)
            continue
        if sub.infeasible:
            continue
        sub_nodes = list(sub.existing_nodes) + list(sub.nodes)
        by_name = {n.name: n for n in sub_nodes}
        seats = [by_name.get(sub.assignments.get(p.name, "")) for p in members]
        if any(s is None for s in seats):
            continue
        _zs, _cs, new_penalty = _spread(seats)
        freed = sum(
            price for name, price in emptied_price.items()
            if not any(not q.is_daemon
                       for q in (by_name.get(name).pods if by_name.get(name) else ()))
        )
        gain = w * (old_penalty - new_penalty) + freed - sub.new_node_cost
        if gain <= 1e-9:
            continue
        if not in_band(members, sub, instance_types):
            continue
        # the pinned copies must not leak into the result (their synthetic
        # zone selector would over-constrain later what-ifs): seat the
        # ORIGINAL pod objects back in their place
        originals = {p.name: p for p in members}
        for n in sub_nodes:
            n.pods = [originals.get(q.name, q) for q in n.pods]
        placed = list(sub.existing_nodes)  # snapshots of base, seats applied
        result.existing_nodes = placed[:n_existing]
        kept = [
            n for n in placed[n_existing:]
            if any(not q.is_daemon for q in n.pods)
        ]
        result.nodes = kept + list(sub.nodes)
        result.assignments.update(sub.assignments)
        return True
    return False


def run_epilogue(
    result,
    pods: Sequence[PodSpec],
    *,
    registry,
    resolve: Optional[Callable[[Sequence[PodSpec]], object]] = None,
    provisioners=(),
    instance_types=(),
    daemonsets=(),
    unavailable=None,
    allow_new_nodes: bool = True,
    max_new_nodes: Optional[int] = None,
    in_band: Optional[Callable] = None,
    allow_pack: bool = True,
    trace=None,
):
    """The gang epilogue: all-or-nothing enforcement, then co-location
    packing, then metrics.  Runs once per top-level solve, after the
    relax rung (gang groups are relax-ineligible, so their scan seats are
    intact here).  Returns the (possibly re-solved) result.

    ``resolve(keep_pods)`` re-solves the batch without a doomed gang's
    members when an in-place retraction would disturb watched constraint
    accounting; without it the epilogue always retracts in place.
    """
    gangs = gang_members(pods)
    if not gangs:
        return result
    t0 = time.perf_counter()
    batch_names = {p.name for ms in gangs.values() for p in ms}
    doomed: Dict[str, GangUnplaced] = {}

    # all-or-nothing: audit, retract, repeat (a re-solve may doom another
    # gang) — bounded by the gang count
    for _ in range(len(gangs) + 1):
        preseated: Optional[Dict[str, int]] = None
        failed: Dict[str, int] = {}
        for gid, members in gangs.items():
            if gid in doomed:
                continue
            placed = sum(1 for p in members if p.name in result.assignments)
            need = declared_size(members)
            if placed == len(members) and placed >= need:
                continue  # whole gang in-batch, fully seated
            # count comembers seated OUTSIDE the batch (delta/consolidation
            # subproblems solve a gang subset against seated comembers)
            if preseated is None:
                preseated = _preseated_counts(result, batch_names)
            total = placed + preseated.get(gid, 0)
            # any unplaced batch member dooms the gang, no matter how many
            # comembers sit elsewhere — partial is partial
            if placed < len(members) or total < need:
                failed[gid] = total
        if not failed:
            break
        retracting: List[PodSpec] = []
        for gid, seated in failed.items():
            members = gangs[gid]
            doomed[gid] = GangUnplaced(gid, declared_size(members), seated)
            retracting.extend(
                p for p in members if p.name in result.assignments)
        if retracting and _watched(retracting, result) and resolve is not None:
            keep = [p for p in pods if gang_of(p) not in doomed]
            try:
                result = resolve(keep)
            # ktlint: allow[KT005] the re-solve is an optimization of the
            # retraction path; on failure fall back to in-place retraction
            # (still correct, possibly conservative for watchers)
            except Exception:
                logger.warning(
                    "gang retraction re-solve failed; retracting in place",
                    exc_info=True)
                _retract_seats(result, retracting)
        else:
            _retract_seats(result, retracting)
        for gid, reason in doomed.items():
            for p in gangs[gid]:
                result.assignments.pop(p.name, None)
                result.infeasible[p.name] = str(reason)

    # co-location packing + accounting for the survivors
    gang_counter = registry.counter(GANG_GANGS)
    zones_hist = registry.histogram(GANG_SPREAD_ZONES)
    classes_hist = registry.histogram(GANG_SPREAD_CLASSES)
    for gid, members in gangs.items():
        if gid in doomed:
            gang_counter.inc({"outcome": "retracted"})
            continue
        whole_batch = all(p.name in result.assignments for p in members)
        outcome = "placed"
        seats = _gang_nodes(result, members) if whole_batch else None
        if seats is not None:
            n_zones, n_classes, penalty = _spread(seats)
            if (allow_pack and penalty > 0.0 and in_band is not None
                    and len(members) == declared_size(members)):
                if _try_pack(
                    result, gid, members,
                    provisioners=provisioners,
                    instance_types=instance_types,
                    daemonsets=daemonsets, unavailable=unavailable,
                    allow_new_nodes=allow_new_nodes,
                    max_new_nodes=max_new_nodes, in_band=in_band,
                    old_penalty=penalty,
                ):
                    outcome = "packed"
                    seats = _gang_nodes(result, members) or seats
                    n_zones, n_classes, _ = _spread(seats)
            zones_hist.observe(float(n_zones))
            classes_hist.observe(float(n_classes))
        gang_counter.inc({"outcome": outcome})
    registry.histogram(GANG_DURATION).observe(time.perf_counter() - t0)
    if trace is not None:
        trace.annotate(
            gangs=len(gangs), gangs_retracted=len(doomed))
    return result


# ---- delta composition (scheduler.solve_delta) --------------------------

def expand_gang_removals(
    prev, removed: Sequence[str],
) -> Tuple[List[str], Dict[str, str]]:
    """A member removal retracts the gang: expand ``removed`` with every
    seated comember of any gang a removed pod belongs to.  Returns the
    expanded name list plus {comember_name: typed GangUnplaced reason} for
    the members retracted on the gang's behalf (the caller surfaces them
    as unplaced — they were not asked to leave, their gang broke)."""
    if not removed:
        return list(removed), {}
    removed_set = set(removed)
    touched: Set[str] = set()
    roster: Dict[str, List[PodSpec]] = {}
    for n in list(prev.existing_nodes) + list(prev.nodes):
        for q in n.pods:
            gid = gang_of(q)
            if not gid:
                continue
            roster.setdefault(gid, []).append(q)
            if q.name in removed_set:
                touched.add(gid)
    if not touched:
        return list(removed), {}
    out = list(removed)
    retracted: Dict[str, str] = {}
    for gid in sorted(touched):
        members = roster.get(gid, [])
        explicit = sum(1 for q in members if q.name in removed_set)
        reason = str(GangUnplaced(
            gid, declared_size(members), len(members) - explicit))
        for q in members:
            if q.name not in removed_set:
                out.append(q.name)
                retracted[q.name] = reason
    return out, retracted


def delta_needs_full(result, added: Sequence[PodSpec]) -> bool:
    """A gang add must place atomically or fall back to the full solve:
    true when any added gang ended (wholly, post-epilogue) unplaced in the
    delta step's result — the incremental tier could not seat it against
    surviving capacity, so the caller re-solves from the stripped base
    (one more chance before the typed GangUnplaced verdict stands)."""
    for gid, members in gang_members(added).items():
        if any(p.name in result.infeasible for p in members):
            return True
    return False


# ---- consolidation composition -----------------------------------------

def nodes_carry_gangs(nodes: Sequence) -> bool:
    """Whether any of ``nodes`` hosts a gang member — consolidation routes
    such candidates through the serial what-if so the gang epilogue (and
    its typed all-or-nothing verdict) judges the eviction, not the raw
    batched feasibility scan."""
    if not gang_enabled():
        return False
    return any(gang_of(q) for n in nodes for q in n.pods)
