#!/usr/bin/env python
"""Render the deploy/ manifests — the values layer.

The manifests carry ``${KT_NAME:-default}`` tokens (env-substitution, the
same one-source-of-truth posture as the reference's generated chart,
reference Makefile:19-29: values come from ONE place instead of being
hand-edited per file).  Render with defaults, or override via environment:

    python deploy/render.py                         # all manifests, stdout
    KT_IMAGE=repo/karpenter-tpu:v4 KT_NAMESPACE=prod \
        python deploy/render.py | kubectl apply -f -
    python deploy/render.py --out build/            # one file per manifest

Values:
    KT_NAMESPACE          target namespace            (karpenter)
    KT_IMAGE              container image             (karpenter-tpu:latest)
    KT_OPERATOR_REPLICAS  operator replicas           (2; leader + standby)
    KT_SOLVER_REPLICAS    solver sidecar replicas     (1 per TPU chip)
    KT_SOLVER_PORT        solver gRPC port            (50151)
    KT_SOLVER_BACKEND     solver backend              (auto)
    KT_METRICS_PORT       operator metrics/health     (8080)

Unknown ``${KT_...}`` tokens are an error (a typo'd token must not ship as
a literal), and rendering is pure stdlib — no helm/kustomize dependency.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from pathlib import Path

TOKEN = re.compile(r"\$\{(KT_[A-Z0-9_]+)(?::-([^}]*))?\}")

#: render order mirrors apply order (rbac before the deployments)
MANIFESTS = ("rbac.yaml", "configmap.yaml", "solver.yaml", "operator.yaml")


def render_text(text: str, env=None) -> str:
    env = os.environ if env is None else env

    def sub(m: re.Match) -> str:
        name, default = m.group(1), m.group(2)
        val = env.get(name, default)
        if val is None:
            raise KeyError(f"token ${{{name}}} has no default and {name} "
                           f"is not set")
        return val

    return TOKEN.sub(sub, text)


def render_all(base: Path = None, env=None) -> dict:
    base = base or Path(__file__).parent
    return {name: render_text((base / name).read_text(), env)
            for name in MANIFESTS}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="deploy/render.py")
    ap.add_argument("--out", default="", help="write per-manifest files here "
                                             "instead of stdout")
    args = ap.parse_args(argv)
    rendered = render_all()
    if args.out:
        out = Path(args.out)
        out.mkdir(parents=True, exist_ok=True)
        for name, text in rendered.items():
            (out / name).write_text(text)
            print(f"wrote {out / name}", file=sys.stderr)
    else:
        try:
            print("\n---\n".join(rendered[n].strip() for n in MANIFESTS))
        except BrokenPipeError:  # | head — not an error
            sys.stderr.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
